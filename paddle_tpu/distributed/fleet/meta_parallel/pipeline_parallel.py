"""Pipeline-parallel runtime: the microbatch schedule as ONE compiled program.

Reference parity: fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py (U) — `PipelineParallel.train_batch` running
1F1B/GPipe microbatch schedules with NCCL p2p between stage ranks
(SURVEY.md §2.2 P13, §3.3 step 4).

TPU-native design: no p2p runtime, no shape negotiation, no interceptor
actors. The whole schedule is data: a `lax.scan` over ticks inside
`shard_map` over the 'pp' mesh axis; at each tick every device runs its
stage (one `lax.switch` branch — embedding stage consumes the raw
microbatch, the final stage computes the loss) and hands its activation to
the next stage with a ring `lax.ppermute`. XLA overlaps the permute with
compute (the reference needs dedicated comm streams + event sync for this,
SURVEY.md §2.1 N13). Backward is `jax.grad` through the scan, with
`jax.checkpoint` per stage giving the recompute variant (ref
recompute_interval). Warmup/drain bubbles are masked ticks, matching GPipe.

Memory semantics (measured via compiled memory_analysis, see
tests/test_pipeline_parallel.py::TestPipelineMemory): this is GPipe-shaped,
NOT true 1F1B — `jax.grad` through the scan retains per-tick residuals, so
activation memory grows O(accumulate_steps). With recompute_interval>0 the
per-tick residual is only the tick's BOUNDARY tensors (microbatch input +
ppermuted hidden + labels; measured ≈1× boundary size per microbatch, ~5×
smaller than the no-remat variant), so the growth constant is small: for
transformer stages whose internal activations are 30–60× the boundary
hidden, remat-GPipe uses LESS activation memory than true 1F1B's
O(depth × full-activations) whenever accumulate_steps < ~30× depth, at the
usual one-extra-forward cost. The reference's literal 1F1B schedule
(pp_utils/p2p_communication.py (U)) bounds in-flight FULL activations by
pipeline depth instead — better only for long schedules without remat.

Gradient flow across stages needs no reducer: stage params enter replicated
(in_spec P()), so shard_map's transpose inserts the psum that sums each
param's gradient from its owning stage (zeros elsewhere) — and the same psum
doubles as the dp gradient all-reduce when the 'dp' axis is live.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ....core import random as random_state
from ....core import tape as _tape
from ....core.op_call import apply
from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from ... import collective_ctx
from ...topology import get_hybrid_communicate_group
from .parallel_layers.pp_layers import PipelineLayer

try:
    from jax import shard_map
except ImportError:  # older jax layout
    from jax.experimental.shard_map import shard_map


@jax.custom_vjp
def _grad_scale(x, s):
    return x


def _grad_scale_fwd(x, s):
    return x, s


def _grad_scale_bwd(s, g):
    return (g * s, None)


_grad_scale.defvjp(_grad_scale_fwd, _grad_scale_bwd)


class PipelineParallel(Layer):
    """ref PipelineParallel (meta_parallel): wraps a PipelineLayer and runs
    the compiled microbatch schedule. Composition with dp is native (batch
    sharded over 'dp'); with mp, stage layers built from mpu mp-layers run
    in explicit shard mode — their params enter shard_map pre-sharded over
    the 'mp' axis and the layers issue the Megatron collectives inline."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        cfg = {}
        if strategy is not None:
            cfg = getattr(strategy, "pipeline_configs", None) or (
                strategy if isinstance(strategy, dict) else {})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self._train_step = None
        self._pp_fn_cache = {}

    # ----------------------------------------------------------- plumbing
    def forward(self, x):
        return self._layers(x)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()

    # ----------------------------------------------------------- schedule
    def _schedule_env(self):
        """Setup shared by every schedule builder: mesh axis liveness,
        per-param shard_map specs (pp×mp composition: mp-layer params with
        is_distributed enter pre-sharded over 'mp' via their hint, the rest
        replicated), and the mp cotangent-rescale wrapper.

        On the rescale: the replicated scalar loss (out_specs P()) seeds
        each shard with cotangent 1/N_mesh; the psum-over-pp transpose
        restores the pp factor and the replicated-param transpose psums over
        'mp' (identical grads on every mp rank), so replicated params come
        out exact — but mp-SHARDED params have no mp psum and land at 1/mp
        of the true grad, so their cotangent gets scaled back by mp."""
        pp = self._layers
        mesh = self._hcg.mesh
        names = list(pp.state_dict())
        dp_live = "dp" in mesh.shape and mesh.shape["dp"] > 1
        mp_live = "mp" in mesh.shape and mesh.shape["mp"] > 1
        live_axes = ("pp", "mp") if mp_live else ("pp",)
        sd0 = pp.state_dict()

        def _param_spec(t):
            axes = getattr(t, "_sharding_axes", None)
            if mp_live and getattr(t, "is_distributed", False) and axes:
                return P(*axes)
            return P()

        param_specs = tuple(_param_spec(sd0[n]) for n in names)

        def rescale_mp(params):
            if not mp_live:
                return params
            mp_size = float(mesh.shape["mp"])
            return tuple(_grad_scale(p, mp_size) if spec != P() else p
                         for p, spec in zip(params, param_specs))

        batch_spec = P(None, "dp") if dp_live else P()
        return (mesh, names, dp_live, mp_live, live_axes, param_specs,
                rescale_mp, batch_spec)

    @staticmethod
    def _run_items(items, t_in):
        for it in items:
            t_in = it(t_in)
        return t_in

    def _pipeline_pure_fn(self, n_micro):
        """Build pure(x_mbs, y_mbs, key, *params) -> scalar loss, shard_mapped
        over the hybrid mesh with the tick loop inside."""
        if n_micro in self._pp_fn_cache:
            return self._pp_fn_cache[n_micro]

        pp = self._layers
        S = pp.num_stages
        V = getattr(pp, "num_virtual_stages", 1)
        if V > 1:
            return self._pipeline_pure_fn_interleaved(n_micro)
        remat = pp._recompute_interval and pp._recompute_interval > 0
        (mesh, names, dp_live, mp_live, live_axes, param_specs,
         rescale_mp, batch_spec) = self._schedule_env()
        run_items = self._run_items

        def spmd(x_mbs, y_mbs, base_key, *params):
            s = lax.axis_index("pp")
            params = rescale_mp(params)

            with _tape.no_grad(), collective_ctx.axis_scope(*live_axes), \
                    pp.use_state(dict(zip(names, params))):

                def make_branch(k):
                    items = pp.get_stage_layers(k)
                    is_last = k == S - 1

                    def br(x_mb, hid, y_mb, key):
                        with random_state.fork_rng(key):
                            if S == 1:
                                out = run_items(items, Tensor(x_mb))
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return hid, jnp.mean(loss._data).astype(jnp.float32)
                            if is_last:
                                out = run_items(items, Tensor(hid))
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return hid, jnp.mean(loss._data).astype(jnp.float32)
                            src = Tensor(x_mb) if k == 0 else Tensor(hid)
                            out = run_items(items, src)
                            return (out._data.astype(hid.dtype),
                                    jnp.zeros((), jnp.float32))

                    return jax.checkpoint(br) if remat else br

                branches = [make_branch(k) for k in range(S)]

                # hidden buffer: shape/dtype of stage 0's output
                def stage0_shape(x_mb, key):
                    with random_state.fork_rng(key):
                        out = run_items(pp.get_stage_layers(0), Tensor(x_mb))
                    return out._data

                probe_key = jax.random.fold_in(base_key, 0)
                if S > 1:
                    hid_sd = jax.eval_shape(stage0_shape, x_mbs[0], probe_key)
                else:
                    hid_sd = jax.eval_shape(lambda a: a[..., :1].astype(jnp.float32),
                                            x_mbs[0])
                hid0 = jnp.zeros(hid_sd.shape, hid_sd.dtype)

                T = n_micro + S - 1
                perm = [(i, (i + 1) % S) for i in range(S)]

                def tick(carry, t):
                    hid, loss_sum = carry
                    key_t = jax.random.fold_in(base_key, t)
                    m0 = jnp.clip(t, 0, n_micro - 1)
                    mL = jnp.clip(t - (S - 1), 0, n_micro - 1)
                    x_mb = jnp.take(x_mbs, m0, axis=0)
                    y_mb = jnp.take(y_mbs, mL, axis=0)
                    hid_next, loss_t = lax.switch(
                        jnp.minimum(s, S - 1), branches, x_mb, hid, y_mb, key_t)
                    valid = (t >= S - 1) & (t - (S - 1) < n_micro)
                    loss_sum = loss_sum + jnp.where(valid, loss_t, 0.0)
                    if S > 1:
                        hid_next = lax.ppermute(hid_next, "pp", perm)
                    return (hid_next, loss_sum), None

                (_, loss_sum), _ = lax.scan(
                    tick, (hid0, jnp.zeros((), jnp.float32)), jnp.arange(T))

            loss = lax.psum(loss_sum, "pp") / n_micro if S > 1 else loss_sum / n_micro
            if dp_live:
                loss = lax.pmean(loss, "dp")
            return loss

        def pure(x_mbs, y_mbs, base_key, *params):
            f = shard_map(
                spmd, mesh=mesh,
                in_specs=(batch_spec, batch_spec, P()) + param_specs,
                out_specs=P(), check_vma=False)
            return f(x_mbs, y_mbs, base_key, *params)

        self._pp_fn_cache[n_micro] = (pure, names)
        return self._pp_fn_cache[n_micro]

    def _pipeline_pure_fn_interleaved(self, n_micro):
        """Interleaved / VPP schedule (ref Megatron-style interleaved 1F1B,
        fleet pipeline_parallel.py with num_virtual_pipeline_stages): the
        model is cut into S·V chunks, rank r owns chunks {r, r+S, ...}; per
        tick every rank runs its V chunks (slot j carries sweep j's
        activation) and the ring ppermutes all V slots at once, with rank 0
        shifting slot j-1's arrival into slot j (sweep boundary)."""
        key = ("vpp", n_micro)
        if key in self._pp_fn_cache:
            return self._pp_fn_cache[key]

        pp = self._layers
        S = pp.num_stages
        V = pp.num_virtual_stages
        D = S * V
        if S == 1:
            raise ValueError("num_virtual_pipeline_stages>1 requires pp>1")
        remat = pp._recompute_interval and pp._recompute_interval > 0
        (mesh, names, dp_live, mp_live, live_axes, param_specs,
         rescale_mp, batch_spec) = self._schedule_env()
        run_items = self._run_items

        def spmd(x_mbs, y_mbs, base_key, *params):
            s = lax.axis_index("pp")
            params = rescale_mp(params)

            with _tape.no_grad(), collective_ctx.axis_scope(*live_axes), \
                    pp.use_state(dict(zip(names, params))):

                def make_chunk_branch(d):
                    items = pp.get_stage_layers(d)
                    is_last = d == D - 1

                    def br(x_mb, hid, y_mb, key):
                        with random_state.fork_rng(key):
                            src = Tensor(x_mb) if d == 0 else Tensor(hid)
                            if is_last:
                                out = run_items(items, src)
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return hid, jnp.mean(loss._data).astype(jnp.float32)
                            out = run_items(items, src)
                            return (out._data.astype(hid.dtype),
                                    jnp.zeros((), jnp.float32))

                    return jax.checkpoint(br) if remat else br

                # slot j on rank r runs chunk j*S + r
                branches = [[make_chunk_branch(j * S + r) for r in range(S)]
                            for j in range(V)]

                def stage0_shape(x_mb, key):
                    with random_state.fork_rng(key):
                        out = run_items(pp.get_stage_layers(0), Tensor(x_mb))
                    return out._data

                probe_key = jax.random.fold_in(base_key, 0)
                hid_sd = jax.eval_shape(stage0_shape, x_mbs[0], probe_key)
                hid0 = jnp.zeros((V,) + hid_sd.shape, hid_sd.dtype)

                T = n_micro + D - 1
                perm = [(i, (i + 1) % S) for i in range(S)]

                def tick(carry, t):
                    hid, loss_sum = carry          # hid [V, ...hidden]
                    key_t = jax.random.fold_in(base_key, t)
                    m0 = jnp.clip(t, 0, n_micro - 1)
                    mL = jnp.clip(t - (D - 1), 0, n_micro - 1)
                    x_mb = jnp.take(x_mbs, m0, axis=0)
                    y_mb = jnp.take(y_mbs, mL, axis=0)
                    outs = []
                    loss_t = jnp.zeros((), jnp.float32)
                    for j in range(V):
                        h_j, l_j = lax.switch(jnp.minimum(s, S - 1),
                                              branches[j], x_mb, hid[j],
                                              y_mb, jax.random.fold_in(key_t, j))
                        outs.append(h_j)
                        loss_t = loss_t + l_j
                    hid_out = jnp.stack(outs)          # [V, ...]
                    valid = (t >= D - 1) & (t - (D - 1) < n_micro)
                    loss_sum = loss_sum + jnp.where(valid, loss_t, 0.0)
                    permuted = lax.ppermute(hid_out, "pp", perm)
                    # sweep boundary: at rank 0, slot j's next input is what
                    # rank S-1's slot j-1 just sent (slot 0 consumes x_mb)
                    shifted = jnp.concatenate(
                        [jnp.zeros_like(permuted[:1]), permuted[:-1]], axis=0)
                    hid_next = jnp.where(s == 0, shifted, permuted)
                    return (hid_next, loss_sum), None

                (_, loss_sum), _ = lax.scan(
                    tick, (hid0, jnp.zeros((), jnp.float32)), jnp.arange(T))

            loss = lax.psum(loss_sum, "pp") / n_micro
            if dp_live:
                loss = lax.pmean(loss, "dp")
            return loss

        def pure(x_mbs, y_mbs, base_key, *params):
            f = shard_map(
                spmd, mesh=mesh,
                in_specs=(batch_spec, batch_spec, P()) + param_specs,
                out_specs=P(), check_vma=False)
            return f(x_mbs, y_mbs, base_key, *params)

        self._pp_fn_cache[key] = (pure, names)
        return self._pp_fn_cache[key]

    def _loss_fn_for(self, n_micro):
        pure, names = self._pipeline_pure_fn(n_micro)

        def loss_fn(model, x_mbs, y_mbs):
            sd = model.state_dict()
            key = random_state.next_key()
            return apply(pure, x_mbs, y_mbs, key,
                         *[sd[n] for n in names], _op_name="pipeline")

        return loss_fn

    def _split_micro(self, t):
        arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
        n = self.accumulate_steps
        if arr.shape[0] % n:
            raise ValueError(
                f"batch dim {arr.shape[0]} not divisible by accumulate_steps {n}")
        return Tensor(arr.reshape((n, arr.shape[0] // n) + arr.shape[1:]))

    # ----------------------------------------------------------- API
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """ref train_batch: one full fwd+bwd+step over accumulate_steps
        microbatches, compiled once."""
        x, y = data
        x_mbs, y_mbs = self._split_micro(x), self._split_micro(y)
        if self._train_step is None:
            from ....jit.train_step import TrainStep

            self._train_step = TrainStep(
                self._layers, self._loss_fn_for(self.accumulate_steps),
                optimizer, scaler=scaler)
        loss = self._train_step(x_mbs, y_mbs)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        was_training = self._layers.training
        self._layers.eval()
        try:
            with _tape.no_grad():
                out = self._layers(x if isinstance(x, Tensor) else Tensor(x))
                if compute_loss:
                    return self._layers.compute_loss(
                        out, y if isinstance(y, Tensor) else Tensor(y))
                return out
        finally:
            if was_training:
                self._layers.train()
