from .manager import (
    ElasticManager, ElasticStatus,
    MembershipStore, FileMembershipStore, LocalMembershipStore,
)

__all__ = [
    "ElasticManager", "ElasticStatus",
    "MembershipStore", "FileMembershipStore", "LocalMembershipStore",
]
