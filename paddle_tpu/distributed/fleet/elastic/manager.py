"""Elastic training manager (ref: fleet/elastic/manager.py (U)).

The reference watches an etcd prefix for node join/leave and relaunches the
trainer with a new world size. The TPU rebuild keeps the same state machine
(HOLD/COMPLETED/RESTART/EXIT) but swaps etcd for a pluggable membership
store: a shared-filesystem heartbeat directory (works on any TPU pod slice,
where /tmp or NFS is shared per-host) or an in-memory store for tests.
Recovery is checkpoint-autoresume: on membership change the manager asks the
launcher to relaunch the script; the training loop resumes from the latest
sharded checkpoint (distributed/checkpoint reshard-on-load handles a changed
device count).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class MembershipStore:
    """Abstract membership store: register heartbeats, list live nodes."""

    def register(self, node_id: str, meta: dict):
        raise NotImplementedError

    def heartbeat(self, node_id: str):
        raise NotImplementedError

    def deregister(self, node_id: str):
        raise NotImplementedError

    def live_nodes(self, ttl: float) -> dict:
        raise NotImplementedError


class FileMembershipStore(MembershipStore):
    """Heartbeat files under a shared directory — one JSON file per node."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, node_id):
        return os.path.join(self.root, f"node.{node_id}.json")

    def register(self, node_id, meta):
        with open(self._path(node_id), "w") as f:
            json.dump({"meta": meta, "ts": time.time()}, f)

    def heartbeat(self, node_id):
        p = self._path(node_id)
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = {"meta": {}}
        rec["ts"] = time.time()
        with open(p, "w") as f:
            json.dump(rec, f)

    def deregister(self, node_id):
        try:
            os.unlink(self._path(node_id))
        except OSError:
            pass

    def live_nodes(self, ttl):
        now = time.time()
        out = {}
        for fn in os.listdir(self.root):
            if not (fn.startswith("node.") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if now - rec.get("ts", 0) <= ttl:
                out[fn[len("node."):-len(".json")]] = rec.get("meta", {})
        return out


class LocalMembershipStore(MembershipStore):
    """In-process store for unit tests."""

    def __init__(self):
        self._nodes = {}
        self._lock = threading.Lock()

    def register(self, node_id, meta):
        with self._lock:
            self._nodes[node_id] = (meta, time.time())

    def heartbeat(self, node_id):
        with self._lock:
            if node_id in self._nodes:
                meta, _ = self._nodes[node_id]
                self._nodes[node_id] = (meta, time.time())

    def deregister(self, node_id):
        with self._lock:
            self._nodes.pop(node_id, None)

    def live_nodes(self, ttl):
        now = time.time()
        with self._lock:
            return {k: m for k, (m, ts) in self._nodes.items()
                    if now - ts <= ttl}


class ElasticManager:
    """Watches cluster membership; decides HOLD / RESTART / EXIT.

    Paddle semantics kept: `np` may be a fixed int or an "min:max" elastic
    range; below min → HOLD (wait for nodes), change within range → RESTART
    with the new world size, above max → extra nodes told to EXIT.
    """

    def __init__(self, node_id=None, np="1", store=None, heartbeat_interval=1.0,
                 ttl=None):
        self.node_id = node_id or os.getenv("PADDLE_TRAINER_ID", "0")
        lo, _, hi = str(np).partition(":")
        self.min_np = int(lo)
        self.max_np = int(hi) if hi else self.min_np
        self.elastic = self.max_np > self.min_np
        self.store = store if store is not None else FileMembershipStore(
            os.getenv("PADDLE_ELASTIC_DIR", "/tmp/paddle_tpu_elastic"))
        self.interval = heartbeat_interval
        self.ttl = ttl if ttl is not None else 3 * heartbeat_interval
        self._stop = threading.Event()
        self._thread = None
        self._world = None  # membership snapshot at enter()
        self.final_status = None  # set by exit(): COMPLETED or ERROR

    # ------------------------------------------------------------- lifecycle
    def enter(self, meta=None):
        self.store.register(self.node_id, meta or {})
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()
        self._world = sorted(self.store.live_nodes(self.ttl))
        return self

    def exit(self, completed=True):
        if self.final_status is None:  # first exit() wins (a SIGTERM
            # handler's completed=False must survive the finally-block exit)
            self.final_status = (ElasticStatus.COMPLETED if completed
                                 else ElasticStatus.ERROR)
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2 * self.interval)
        self.store.deregister(self.node_id)

    def _beat(self):
        while not self._stop.wait(self.interval):
            self.store.heartbeat(self.node_id)

    # --------------------------------------------------------------- policy
    def poll(self):
        """One membership check → an ElasticStatus decision."""
        live = sorted(self.store.live_nodes(self.ttl))
        n = len(live)
        if n < self.min_np:
            return ElasticStatus.HOLD
        if n > self.max_np:
            # deterministic trim: highest-sorted extras exit
            if self.node_id in live[self.max_np:]:
                return ElasticStatus.EXIT
            live = live[:self.max_np]
            n = self.max_np
        if live != self._world:
            self._world = live
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED

    def watch(self, timeout=None, on_restart=None):
        """Block until a scale event (or timeout). Returns final status."""
        t0 = time.time()
        while True:
            st = self.poll()
            if st == ElasticStatus.RESTART and on_restart is not None:
                on_restart(self.world_size())
            if st in (ElasticStatus.RESTART, ElasticStatus.EXIT):
                return st
            if timeout is not None and time.time() - t0 >= timeout:
                return st
            time.sleep(self.interval)

    def world_size(self):
        return len(self._world or [])

    def signal_handler(self, sig=signal.SIGTERM):
        """Install a handler that deregisters on SIGTERM (preemption)."""
        def h(signum, frame):
            self.exit(completed=False)
            raise SystemExit(128 + signum)

        signal.signal(sig, h)
