"""HybridParallelOptimizer: the optimizer wrapper fleet hands back.

Reference parity: fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py (U) — wraps the user optimizer with (a)
HybridParallelClipGrad (global grad-norm allreduced across mp/pp/sharding
groups), (b) sequence-parallel param grad allreduce, (c) the
distributed_scaler hookup (SURVEY.md §2.2 P18, §3.3 step 6).

TPU-native: grads computed under jit/GSPMD are already *global* values, so
(a) reduces to the stock ClipGradByGlobalNorm (which additionally psums over
any live shard_map axes — see nn/clip.py), and (b) is only needed in the
explicit shard_map regime, where it's an mp-psum over SP-tagged params'
grads applied at step time.
"""

from __future__ import annotations

from .....core import tape as _tape
from .....core.tensor import Tensor
from .... import collective_ctx


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    # grads of sequence-parallel params (LN/bias inside SP regions) see only
    # local tokens — sum them over mp before stepping (ref
    # register_sequence_parallel_allreduce_hooks)
    def _sync_sp_grads(self):
        ax = collective_ctx.current_axis("mp")
        if ax is None:
            return
        import jax

        with _tape.no_grad():
            for p in self._inner_opt._parameter_list:
                if getattr(p, "sequence_parallel", False) and p.grad is not None:
                    p.grad._data = jax.lax.psum(p.grad._data, ax)

    def step(self):
        self._sync_sp_grads()
        return self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return [], []

    def clear_grad(self, set_to_zero=True):
        return self._inner_opt.clear_grad()

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)
