"""Static-graph meta-optimizers (ref: fleet/meta_optimizers/*.py (U),
SURVEY.md §2.2 P20 — there, each meta-optimizer REWRITES the static
ProgramDesc before Executor.run: AMPOptimizer inserts cast ops + loss
scaling, RecomputeOptimizer marks checkpoint segments for the backward,
GradientMergeOptimizer wraps the update in a k-step accumulation,
LambOptimizer swaps the update rule).

TPU-native design: the recorded DAG (static/graph.py) plays the role of
the ProgramDesc, and the rewrites are applied at `minimize()` time by ONE
wrapper returned from `fleet.distributed_optimizer` under
`paddle.enable_static()`:

- **amp** — in-place cast rewrite of the recorded nodes: white-listed ops
  (matmul/conv/...) compute in the amp dtype, black-listed ops
  (softmax/norms/...) in f32 — the same O1 split `amp.auto_cast` applies
  eagerly, but performed as a program transformation. fp16 additionally
  gets dynamic loss scaling compiled INTO the train program
  (Executor._run_train: scaled loss, unscaled grads, skip-update on
  non-finite, grow/shrink bookkeeping). bf16 (TPU default) needs none.
- **recompute** — `recompute_configs["checkpoints"]` (static Tensors) are
  attached to the owning Program; the executor evaluates each
  inter-checkpoint segment under `jax.checkpoint`, so the backward holds
  only checkpoint values, not segment residuals.
- **gradient_merge** — grads accumulate across `k_steps` runs inside the
  compiled program; the parameter/optimizer update applies every k-th run
  (`avg=True` divides by k — exact big-batch equivalence for mean losses).
- **lamb** — the inner optimizer is replaced by Lamb with
  `lamb_configs["lamb_weight_decay"]` and the name-substring
  `exclude_from_weight_decay` list.

Strategies that are mesh-placement concerns on TPU (sharding, dp/mp/pp)
are NOT program rewrites here — GSPMD + the fleet wrappers own them
(SURVEY.md §7 design stance); localsgd/dgc stay out of scope.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _iter_nodes(root_syms):
    from ....static.graph import _SymArr

    seen, stack = set(), [s.node for s in root_syms if s.node is not None]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        yield n
        for x in n.inputs:
            if isinstance(x, _SymArr) and x.node is not None:
                stack.append(x.node)


def _amp_cast_fn(fn, jd):
    """Wrap a recorded node fn so floating array inputs are cast to `jd`
    before compute — the static analog of op_call._maybe_amp_wrap, using
    the same shared cast rule."""
    from ....core.op_call import amp_cast_arrays

    def wrapped(*arrays, **kw):
        return fn(*amp_cast_arrays(arrays, jd), **kw)

    wrapped._amp_static = jd
    # keep the ORIGINAL fn reachable so a re-rewrite with a different
    # dtype rewraps that, instead of stacking casts where the stale inner
    # one runs last and silently wins (advisor r4); a dedicated attribute
    # avoids colliding with functools.wraps' __wrapped__ on op fns
    wrapped._amp_orig = getattr(fn, "_amp_orig", fn)
    wrapped.__name__ = getattr(fn, "__name__", "op")
    return wrapped


def amp_rewrite(loss, dtype, level="O1", custom_white=(), custom_black=()):
    """In-place white/black-list cast rewrite of every node reachable from
    `loss` (the training subgraph — the static analog of the reference's
    AMP pass over the main program's ops). Idempotent per (node, dtype)."""
    from ....amp.auto_cast import BLACK_LIST, WHITE_LIST
    from ....static.graph import StaticGraphError, _is_sym

    if not _is_sym(loss):
        raise StaticGraphError("amp rewrite expects a static loss Tensor")
    white = set(WHITE_LIST) | set(custom_white)
    black = (set(BLACK_LIST) | set(custom_black)) - set(custom_white)
    n_rewritten = 0
    for node in _iter_nodes([loss._data]):
        name = node.op_name or ""
        if not name:
            continue  # unnamed helpers are never auto-cast (amp parity)
        if name in black:
            jd = jnp.float32
        elif level == "O2" or name in white:
            jd = jnp.dtype(dtype)
        else:
            continue
        if getattr(node.fn, "_amp_static", None) == jd:
            continue
        # rewrap the original fn, not the wrapper: re-minimizing with a
        # different amp dtype must REPLACE the cast, not stack a second
        node.fn = _amp_cast_fn(getattr(node.fn, "_amp_orig", node.fn), jd)
        n_rewritten += 1
    return n_rewritten


class StaticMetaOptimizer:
    """The optimizer `fleet.distributed_optimizer` returns under static
    mode. Presents the exact Optimizer surface Executor._run_train drives
    (update math, accumulators, clip, lr) by delegating to the possibly-
    swapped inner optimizer, plus the meta attributes the executor
    consults (`_static_amp_scaler`, `_gm_k`, `_gm_avg`)."""

    def __init__(self, optimizer, strategy):
        from ..base.distributed_strategy import DistributedStrategy

        self.__dict__["_inner"] = optimizer
        self.__dict__["_strategy"] = strategy or DistributedStrategy()
        self.__dict__["_static_amp_scaler"] = None
        self.__dict__["_static_dp_mesh"] = None
        self.__dict__["_gm_k"] = 1
        self.__dict__["_gm_avg"] = True
        self.__dict__["_gm_buffers"] = None
        self.__dict__["_gm_nacc"] = None
        self.__dict__["_gm_count"] = 0

    # -- surface the executor mutates: route to the inner optimizer.
    # __getattr__ delegates every read not found locally (incl.
    # _parameter_list/_step_count/_accumulators), and __setattr__ routes
    # every write that isn't a meta attribute to the inner optimizer — so
    # register_minimize/Executor mutate the REAL optimizer's state.
    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)

    def __setattr__(self, name, value):
        if name in self.__dict__ or name in (
                "_static_amp_scaler", "_gm_k", "_gm_avg", "_gm_buffers",
                "_gm_nacc", "_gm_count"):
            self.__dict__[name] = value
        else:
            setattr(self.__dict__["_inner"], name, value)

    @property
    def inner_opt(self):
        return self._inner

    # ------------------------------------------------------------ minimize
    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ....static.graph import (StaticGraphError, _is_sym,
                                      _owning_program, register_minimize)

        if not _is_sym(loss):
            raise StaticGraphError(
                "StaticMetaOptimizer.minimize expects a static loss Tensor "
                "(build the model under paddle.enable_static())")
        strat = self._strategy

        if getattr(strat, "lamb", False):
            self.__dict__["_inner"] = self._as_lamb()

        if getattr(strat, "amp", False):
            cfg = strat.amp_configs
            use_bf16 = bool(cfg.get("use_bf16", True))
            dtype = jnp.bfloat16 if use_bf16 else jnp.float16
            level = "O2" if cfg.get("use_pure_fp16") else "O1"
            amp_rewrite(loss, dtype, level,
                        custom_white=cfg.get("custom_white_list") or (),
                        custom_black=cfg.get("custom_black_list") or ())
            if not use_bf16:
                # fp16 trains behind dynamic loss scaling, compiled into
                # the train program by Executor._run_train
                self._static_amp_scaler = {
                    "cfg": dict(cfg),
                    "state": {
                        "scale": jnp.asarray(
                            float(cfg.get("init_loss_scaling", 32768.0)),
                            jnp.float32),
                        "good": jnp.asarray(0, jnp.int32),
                        "bad": jnp.asarray(0, jnp.int32),
                    },
                }

        if getattr(strat, "gradient_merge", False):
            gm = strat.gradient_merge_configs
            self._gm_k = max(1, int(gm.get("k_steps", 1)))
            self._gm_avg = bool(gm.get("avg", True))
            self._gm_buffers = None
            self._gm_count = 0

        result = register_minimize(self, loss, parameters=parameters,
                                   no_grad_set=no_grad_set)

        # static DATA-PARALLEL training (the reference's historical fleet
        # static path: transpiled program + grad allreduce): when the
        # hybrid mesh has a dp axis, the executor compiles the train
        # program with feeds sharded over it and params replicated —
        # GSPMD inserts the gradient all-reduce (SURVEY.md §3.3/§3.5)
        from ...topology import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        if hcg is not None and (
                hcg.get_data_parallel_world_size() > 1
                or hcg.get_model_parallel_world_size() > 1
                or hcg.get_sharding_parallel_world_size() > 1):
            # dp: feeds shard over 'dp', GSPMD allreduces grads. mp (r5):
            # params shard over 'mp' (static tensor parallel). sharding
            # (r5): optimizer state shards over 'sharding' (static
            # ZeRO-1) — see static/graph.py _mp_state_shardings
            self._static_dp_mesh = hcg.mesh
            self._static_mp_placed = False   # re-place on re-minimize

        if getattr(strat, "recompute", False):
            cks = strat.recompute_configs.get("checkpoints") or []
            syms = []
            for t in cks:
                data = getattr(t, "_data", t)
                if not hasattr(data, "aval"):
                    raise StaticGraphError(
                        "recompute_configs['checkpoints'] must be static "
                        "Tensors from the recorded program")
                syms.append(data)
            _owning_program([loss._data])._recompute_checkpoints = syms
        return result

    def _as_lamb(self):
        from ....optimizer.optimizers import Lamb

        inner = self._inner
        if isinstance(inner, Lamb):
            return inner
        cfg = self._strategy.lamb_configs
        excl = [s for s in (cfg.get("exclude_from_weight_decay") or [])]
        fn = (lambda p: any(s in (p.name or "") for s in excl)) \
            if excl else None
        # an Adam-family inner optimizer keeps its betas/epsilon across the
        # swap (reference LambOptimizer inherits the inner hyperparams)
        return Lamb(
            learning_rate=inner._learning_rate,
            lamb_weight_decay=float(cfg.get("lamb_weight_decay", 0.01)),
            beta1=float(getattr(inner, "_beta1", 0.9)),
            beta2=float(getattr(inner, "_beta2", 0.999)),
            epsilon=float(getattr(inner, "_epsilon", 1e-6)),
            parameters=inner._parameter_list,
            grad_clip=inner._grad_clip,
            exclude_from_weight_decay_fn=fn,
        )

    # dygraph-surface passthroughs (so scripts probing the wrapper work)
    def step(self):
        return self._inner.step()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    @property
    def loss_scaling(self):
        """Current dynamic loss scale (fp16 amp), reference-parity probe."""
        s = self._static_amp_scaler
        return float(s["state"]["scale"]) if s else 1.0

    def get_loss_scaling(self):
        """ref OptimizerWithMixedPrecision.get_loss_scaling."""
        return self.loss_scaling

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        """ref OptimizerWithMixedPrecision.amp_init: casts fp32 params for
        pure-fp16 execution. Here the cast rewrite already feeds every
        white-listed op the amp dtype at run time (parameters stay f32
        master weights), so initialization is a no-op kept for script
        parity."""
        return None
