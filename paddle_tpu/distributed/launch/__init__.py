"""`python -m paddle_tpu.distributed.launch` — the training launcher.

Reference parity: python/paddle/distributed/launch/ (U) — Context →
CollectiveController, pod/job model, rendezvous masters, env injection,
per-rank log capture, watcher (SURVEY.md §2.2 P21).

TPU-native design: ONE process per host (all local chips belong to a single
jax process), so there is no per-GPU process fan-out; the controller's job
reduces to (a) exporting the env contract (PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS, kept name-compatible so
reference scripts port unchanged) for `jax.distributed.initialize`'s
coordination service (which replaces TCPStore/ETCDMaster), (b) per-rank log
redirection, and (c) the watcher: restart-on-failure with checkpoint
autoresume (the reference's elastic manager collapses to this under jax's
fixed-slice model — membership changes mean a new slice, not an in-place
rescale).
"""

from .main import launch, main  # noqa: F401
