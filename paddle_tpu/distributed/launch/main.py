"""Launcher implementation (see package docstring; ref launch/main.py (U))."""

from __future__ import annotations

import argparse
import os
import runpy
import subprocess
import sys
import time


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="TPU training launcher (one process per host)")
    p.add_argument("--nnodes", type=str, default="1",
                   help="node count or range 'N' / 'N:M'")
    p.add_argument("--master", type=str, default=None,
                   help="coordinator address host:port (rank-0 host)")
    p.add_argument("--rank", type=int,
                   default=int(os.getenv("POD_RANK", os.getenv("RANK", "0"))),
                   help="this host's rank in [0, nnodes)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="write per-rank logs to this dir")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="watcher: relaunch the script this many times on "
                        "failure (autoresume from user checkpoints)")
    p.add_argument("--elastic_np", type=str, default=None,
                   help="elastic mode: 'min:max' node range; membership is "
                        "tracked via PADDLE_ELASTIC_DIR heartbeats and a "
                        "scale event relaunches the script (ref fleet "
                        "elastic, SURVEY.md §5)")
    p.add_argument("--devices", "--gpus", "--tpus", type=str, default=None,
                   help="visible device ids (TPU: informational)")
    p.add_argument("script", type=str, help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _export_env(args):
    nnodes = int(str(args.nnodes).split(":")[0])
    env = {
        "PADDLE_TRAINER_ID": str(args.rank),
        "PADDLE_TRAINERS_NUM": str(nnodes),
        "RANK": str(args.rank),
        "WORLD_SIZE": str(nnodes),
    }
    if args.master:
        eps = [args.master]
        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(eps)
        env["PADDLE_CURRENT_ENDPOINT"] = args.master if args.rank == 0 else ""
        env["MASTER_ADDR"], _, port = args.master.partition(":")
        env["MASTER_PORT"] = port or "8090"
    if args.devices:
        env["FLAGS_selected_tpus"] = args.devices
    os.environ.update(env)
    return env


# a crashed run only resets the restart budget if it survived this long —
# longer than any plausible startup + XLA compile, so deterministic
# post-startup crashes still exhaust max_restarts
_RECOVERY_SECS = float(os.getenv("PADDLE_ELASTIC_RECOVERY_SECS", "300"))


def _run_elastic(args):
    """Elastic supervisor: register membership, run the trainer as a
    subprocess, relaunch on scale events (autoresume from checkpoints)."""
    from ..fleet.elastic import ElasticManager, ElasticStatus

    import signal as _signal

    mgr = ElasticManager(node_id=str(args.rank), np=args.elastic_np).enter()
    current = {"proc": None}

    def _on_term(signum, frame):
        # deregister AND take the trainer down with us — an orphaned trainer
        # would keep training against the shrunken membership's checkpoints.
        # terminate -> wait -> kill escalation mirrors the in-loop teardown.
        p = current["proc"]
        if p is not None and p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        mgr.exit(completed=False)
        raise SystemExit(128 + signum)

    _signal.signal(_signal.SIGTERM, _on_term)
    failures = 0
    try:
        while True:
            # wait for quorum
            while mgr.poll() == ElasticStatus.HOLD:
                time.sleep(mgr.interval)
            if mgr.poll() == ElasticStatus.EXIT:
                print("[launch.elastic] above max_np; exiting", file=sys.stderr)
                return 0
            world = mgr.world_size()
            env = dict(os.environ,
                       PADDLE_TRAINERS_NUM=str(world),
                       WORLD_SIZE=str(world))
            started = time.time()
            current["proc"] = proc = subprocess.Popen(
                [sys.executable, args.script] + list(args.script_args), env=env)
            # watch for membership change while the trainer runs
            status = None
            while proc.poll() is None:
                status = mgr.poll()
                if status in (ElasticStatus.RESTART, ElasticStatus.EXIT):
                    proc.terminate()
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                    break
                time.sleep(mgr.interval)
            if status == ElasticStatus.EXIT:
                return 0
            if status == ElasticStatus.RESTART:
                print(f"[launch.elastic] scale event -> world={mgr.world_size()}; "
                      f"relaunching (autoresume from checkpoint)", file=sys.stderr)
                continue
            rc = proc.returncode
            current["proc"] = None
            if rc == 0:
                return 0
            if time.time() - started > _RECOVERY_SECS:
                # ran productively for a while before this crash — treat it
                # as a NEW incident (restart budgets are per-incident). The
                # threshold must exceed startup+XLA-compile time or a
                # deterministic post-startup crash would loop forever.
                failures = 0
            failures += 1
            if failures > args.max_restarts:
                print(f"[launch.elastic] trainer failed rc={rc}; restarts "
                      f"exhausted ({args.max_restarts})", file=sys.stderr)
                return rc
            print(f"[launch.elastic] trainer failed rc={rc}; relaunch "
                  f"({failures}/{args.max_restarts})", file=sys.stderr)
            time.sleep(3 * mgr.interval)
    finally:
        mgr.exit()


def launch(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    _export_env(args)

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    if args.elastic_np:
        return _run_elastic(args)

    attempt = 0
    while True:
        if attempt == 0 and not args.log_dir and not args.master:
            # common case: run in-process (no fork) — jax owns the devices.
            # Multi-node runs (--master) MUST fork instead: this launcher
            # process already imported paddle_tpu (touching the XLA
            # backend), and the coordination-service rendezvous has to
            # happen before the backend initializes in the training process.
            sys.argv = [args.script] + list(args.script_args)
            runpy.run_path(args.script, run_name="__main__")
            return 0
        # watcher mode: subprocess so a crash can be observed and restarted
        log = None
        if args.log_dir:
            log = open(os.path.join(
                args.log_dir, f"workerlog.{args.rank}.{attempt}"), "w")
        child_env = dict(os.environ)
        # the worker must resolve imports from the launch cwd, like the
        # in-process path does (script dir becomes sys.path[0] otherwise)
        child_env["PYTHONPATH"] = os.getcwd() + os.pathsep + \
            child_env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, args.script] + list(args.script_args),
            stdout=log or None, stderr=subprocess.STDOUT if log else None,
            env=child_env)
        if log:
            log.close()
        if proc.returncode == 0:
            return 0
        if attempt >= args.max_restarts:
            print(f"[launch] worker failed (rc={proc.returncode}), "
                  f"restarts exhausted", file=sys.stderr)
            return proc.returncode
        attempt += 1
        print(f"[launch] worker failed (rc={proc.returncode}); restart "
              f"{attempt}/{args.max_restarts} (autoresume from checkpoint)",
              file=sys.stderr)
        time.sleep(3)


def main():
    raise SystemExit(launch())


if __name__ == "__main__":
    main()
