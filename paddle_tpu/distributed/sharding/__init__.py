"""ZeRO parameter/gradient/optimizer-state sharding.

Reference parity: python/paddle/distributed/sharding/group_sharded.py +
fleet/meta_parallel/sharding/ (U) — `group_sharded_parallel` stages os /
os_g / p_g_os a.k.a. ZeRO-1/2/3 with optional CPU offload (SURVEY.md §2.2
P14).
"""

from .group_sharded import (
    GroupShardedStage2,
    GroupShardedStage3,
    GroupShardedTrainStep,
    DygraphShardingOptimizer,
    group_sharded_parallel,
    save_group_sharded_model,
    sharding_spec_for,
)

__all__ = [
    "GroupShardedStage2", "GroupShardedStage3", "GroupShardedTrainStep",
    "DygraphShardingOptimizer", "group_sharded_parallel",
    "save_group_sharded_model", "sharding_spec_for",
]
