"""ZeRO sharding over the 'sharding' mesh axis.

Reference parity (SURVEY.md §2.2 P14):
  * stage 1 / 'os'      — DygraphShardingOptimizer: optimizer states sharded
  * stage 2 / 'os_g'    — GroupShardedOptimizerStage2 + GroupShardedStage2:
                          grads reduce-scattered, opt states sharded
  * stage 3 / 'p_g_os'  — GroupShardedStage3: params sliced, all-gather on
                          use, release after backward, optional CPU offload

TPU-native design: ZeRO is a *placement policy*, not a runtime. The reference
hand-codes param slicing, bucketed reduce-scatter hooks, and re-allgather
(group_sharded_stage{2,3}.py (U), ~12k LoC of CUDA-stream choreography); under
GSPMD the identical dataflow falls out of jit in/out shardings:

  * stage 1/2: params replicated in/out, optimizer states sharded over
    'sharding' → XLA reduce-scatters grads into the local update and
    all-gathers updated params (exactly ZeRO-2's comm pattern, overlapped by
    the latency-hiding scheduler).
  * stage 3: params sharded in/out as well → XLA all-gathers weights just
    before use and frees them after (FSDP), with the batch additionally
    data-parallel over the same axis, matching the reference's semantics
    where the sharding group is also a data group.
  * offload: optimizer states placed in `pinned_host` memory space
    (jax memories API) — the north star's stage-2/3 host-offload.

The sharded train step below is the load-bearing artifact; the Stage2/Stage3
Layer wrappers and `group_sharded_parallel` keep the reference's API shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...jit.train_step import TrainStep
from ...nn.layer.layers import Layer
from ..topology import get_hybrid_communicate_group

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def sharding_spec_for(shape, degree, axis="sharding"):
    """Pick the first dim divisible by the sharding degree (dim 0 preferred —
    params are stored so the vocab/row dim leads); replicate if none."""
    for d, size in enumerate(shape):
        if size >= degree and size % degree == 0:
            return P(*([None] * d + [axis]))
    return P()


def _mesh_or_default(mesh):
    if mesh is not None:
        return mesh
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError(
            "group_sharded: no mesh — call "
            "fleet.init / create_hybrid_communicate_group(sharding=N) first")
    return hcg.mesh


class GroupShardedTrainStep(TrainStep):
    """One compiled ZeRO step: jit with in/out shardings placing params
    (stage 3) and optimizer states (all stages) on the 'sharding' axis, the
    batch data-parallel over ('dp', 'sharding')."""

    def __init__(self, model, loss_fn, optimizer, level="p_g_os", scaler=None,
                 mesh=None, offload=False, axis="sharding", donate=True):
        # auto_layout=False: this subclass jits with its OWN mesh shardings
        # per batch arity — the inherited AUTO-layout path would bypass them
        super().__init__(model, loss_fn, optimizer, scaler=scaler,
                         donate=donate, auto_layout=False)
        if level not in _LEVELS:
            raise ValueError(f"level must be one of {list(_LEVELS)}, got {level!r}")
        self.level = level
        self.stage = _LEVELS[level]
        self.offload = offload
        self.axis = axis
        self.mesh = _mesh_or_default(mesh)
        self.degree = self.mesh.shape[axis]
        self._placed = False

    # -------------------------------------------------- sharding layout
    def _param_sharding(self, shape):
        if self.stage >= 3:
            return NamedSharding(self.mesh, sharding_spec_for(shape, self.degree, self.axis))
        return NamedSharding(self.mesh, P())

    def _state_sharding(self, shape):
        spec = sharding_spec_for(shape, self.degree, self.axis)
        kwargs = {}
        if self.offload:
            try:
                return NamedSharding(self.mesh, spec, memory_kind="pinned_host")
            except Exception:
                pass  # backend without memories support: keep on device
        return NamedSharding(self.mesh, spec, **kwargs)

    def _batch_sharding(self, ndim):
        axes = [a for a in ("dp", self.axis) if a in self.mesh.shape
                and self.mesh.shape[a] > 1]
        if not axes or ndim == 0:
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, P(tuple(axes)))

    # -------------------------------------------------- build with shardings
    def _build(self):
        step_fn = self._make_step_fn()
        sd = self.model.state_dict()

        param_sh = [self._param_sharding(sd[n]._data.shape) for n in self._param_names]
        buffer_sh = [NamedSharding(self.mesh, P()) for _ in self._buffer_names]
        self._param_sh = param_sh

        opt_states = [self.optimizer._accumulators[id(sd[n])]
                      for n in self._param_names]
        state_sh = [jax.tree.map(
            lambda a, _n=n: self._state_sharding(jnp.shape(a)), st)
            for n, st in zip(self._param_names, opt_states)]
        self._state_sh = state_sh

        rep = NamedSharding(self.mesh, P())
        scaler_sh = (rep, rep, rep) if self.scaler is not None else ()

        in_sh = (param_sh, buffer_sh, state_sh, rep, rep, scaler_sh)
        # trailing None: aux outputs (has_aux loss_fns) stay unconstrained
        out_sh = (param_sh, buffer_sh, state_sh, rep, scaler_sh, None)
        donate = (0, 2) if self.donate else ()

        def jit_with_batch(nbatch, batch_ndims):
            batch_sh = tuple(self._batch_sharding(nd) for nd in batch_ndims)
            return jax.jit(step_fn, donate_argnums=donate,
                           in_shardings=in_sh + batch_sh,
                           out_shardings=out_sh)

        self._jit_cache = {}
        self._raw_step_fn = step_fn

        def dispatch(*args):
            batch = args[6:]
            key = tuple(jnp.ndim(b) for b in batch)
            if key not in self._jit_cache:
                self._jit_cache[key] = jit_with_batch(len(batch), key)
            return self._jit_cache[key](*args)

        self._jitted = dispatch

    def _place_states(self):
        """One-time device_put of params/opt states to their ZeRO placement
        (the reference's param-slicing step in GroupShardedStage3.__init__)."""
        if self._placed:
            return
        sd = self.model.state_dict()
        for n in self._param_names:
            p = sd[n]
            p._data = jax.device_put(p._data, self._param_sharding(p._data.shape))
        opt = self.optimizer
        for n in self._param_names:
            p = sd[n]
            st = opt._accumulators[id(p)]
            opt._accumulators[id(p)] = jax.tree.map(
                lambda a: jax.device_put(a, self._state_sharding(jnp.shape(a))), st)
        self._placed = True

    def __call__(self, *batch):
        if self._jitted is None:
            self._ensure_states()
            self._build()
            self._place_states()
        return super().__call__(*batch)


class _GroupShardedLayer(Layer):
    """API-parity wrapper (ref GroupShardedStage2/GroupShardedStage3): forward
    delegates; sharded state/ckpt helpers expose the placement."""

    stage = None

    def __init__(self, layer, optimizer=None, group=None, offload=False,
                 sync_buffers=False, **kwargs):
        super().__init__()
        self._layers = layer
        self._optimizer = optimizer
        self._group = group
        self._offload = offload
        for p in layer.parameters():
            p.is_distributed = True

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def get_all_parameters(self):
        """ref stage3.get_all_parameters: re-materialize full (replicated)
        params — here an all-gather via device_put to a replicated sharding."""
        hcg = get_hybrid_communicate_group()
        if hcg is None:
            return self.parameters()
        rep = NamedSharding(hcg.mesh, P())
        for p in self.parameters():
            p._data = jax.device_put(p._data, rep)
        return self.parameters()


class GroupShardedStage2(_GroupShardedLayer):
    stage = 2


class GroupShardedStage3(_GroupShardedLayer):
    stage = 3


class DygraphShardingOptimizer:
    """ref fleet DygraphShardingOptimizer (stage 1): thin proxy whose
    accumulator placement is the sharded one; update math is the inner
    optimizer's."""

    def __init__(self, optimizer, hcg=None, axis="sharding"):
        self._inner_opt = optimizer
        self.axis = axis
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """ref python/paddle/distributed/sharding/group_sharded.py::
    group_sharded_parallel — returns (model, optimizer, scaler) wrapped for
    the requested ZeRO level. The returned model carries
    `build_train_step(loss_fn)` producing the compiled GSPMD ZeRO step."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {list(_LEVELS)}, got {level!r}")

    cls = GroupShardedStage3 if level == "p_g_os" else GroupShardedStage2
    wrapped = cls(model, optimizer=optimizer, group=group, offload=offload,
                  sync_buffers=sync_buffers)
    opt = (DygraphShardingOptimizer(optimizer) if level == "os"
           else optimizer)

    def build_train_step(loss_fn, mesh=None, donate=True):
        return GroupShardedTrainStep(
            model, loss_fn, optimizer, level=level, scaler=scaler,
            mesh=mesh, offload=offload, donate=donate)

    wrapped.build_train_step = build_train_step
    return wrapped, opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """ref save_group_sharded_model: gather full params then save via
    framework.io (each rank holds the full logical arrays under GSPMD, so
    this is a plain save after re-replication)."""
    import os

    from ...framework import io as fio

    layer = model._layers if isinstance(model, _GroupShardedLayer) else model
    if isinstance(model, _GroupShardedLayer):
        model.get_all_parameters()
    os.makedirs(output, exist_ok=True)
    fio.save(layer.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
