"""paddle.distributed functional collectives.

Reference parity: python/paddle/distributed/communication/ (U) —
all_reduce/all_gather/reduce_scatter/broadcast/scatter/alltoall/send/recv over
ProcessGroupNCCL comm rings (SURVEY.md §2.2 P9, §2.1 N13/N14).

TPU-native design — there is ONE communication regime, SPMD: a collective is a
named-axis XLA op (`lax.psum`, `lax.all_gather`, `lax.psum_scatter`,
`lax.all_to_all`, `lax.ppermute`) executed inside `shard_map`/`pjit` over the
device mesh, where XLA schedules it onto ICI/DCN and overlaps it with compute
(replacing the reference's dedicated NCCL comm streams, SURVEY.md §3.2).
Eager calls outside any mapped axis are the world-size-1 degenerate case and
are identity — matching the reference's behavior on a 1-GPU group. Calling an
eager collective on a >1 group is a programming error here (there is no
per-rank eager tensor in single-controller jax) and raises with guidance.

Gradient support: every wrapper routes through `core.op_call.apply`, so tape
autograd records the vjp jax derives for the collective (psum ↔ psum, etc.).

Observability (phase 4): every wrapper ticks the shared
``comms.collective_calls``/``comms.wire_bytes`` families via
``observability.comms.record_collective`` — including the world-size-1
eager identity path, whose wire bytes are 0 by the ring model — so the
eager API and the jaxpr walker feed ONE ledger.  A module-level
``distributed.groups`` provider (registered once at import; by-name
replacement makes re-import idempotent, so create/destroy cycles cannot
accumulate providers) reports live and total-created groups.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..observability import comms as _obs_comms
from ..observability import metrics as _obs_metrics
from . import collective_ctx
from .topology import Group, ReduceOp, get_hybrid_communicate_group

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "broadcast", "scatter", "alltoall", "alltoall_single",
    "send", "recv", "isend", "irecv", "barrier", "wait", "get_group",
    "new_group", "destroy_process_group", "shift",
]

_GROUPS = {}
_GROUPS_CREATED = 0


def _groups_provider():
    return {"live_groups": len(_GROUPS),
            "created_total": _GROUPS_CREATED}


_obs_metrics.register_provider("distributed.groups", _groups_provider)


def _nbytes(x):
    """Best-effort operand bytes of a Tensor/array/tracer (0 when the
    value has no array-like shape — the ledger prefers honest zeros to
    raising inside a collective)."""
    data = getattr(x, "_data", x)
    aval = getattr(data, "aval", None)
    try:
        if aval is not None:
            return int(aval.size) * int(aval.dtype.itemsize)
        return int(data.nbytes)
    except Exception:
        return 0


def _tick(op, group, *operands):
    """Record one collective call on the shared comms ledger.  Called at
    Python-call time: once per eager identity call, once per trace for
    compiled programs (the jaxpr walker owns per-dispatch accounting of
    traced programs; this counter answers "which API paths fire")."""
    try:
        _obs_comms.record_collective(
            op, group.axis_name, group.nranks,
            sum(_nbytes(t) for t in operands))
    except Exception:                # pragma: no cover - defensive
        pass


def _default_group():
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        # world group over every mesh axis is rarely what callers want; the
        # default eager group is the data-parallel group, matching the
        # reference's default comm group for DataParallel scripts
        return hcg.get_data_parallel_group()
    return Group(axis_name=None, nranks=1)


def _resolve(group):
    if group is None:
        return _default_group()
    return group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    """Create a Group. TPU-native: a group must correspond to a mesh axis to
    be usable inside compiled code; `axis_name` picks it. Plain rank lists
    produce an opaque group usable only for bookkeeping/world-size-1."""
    global _GROUPS_CREATED
    g = Group(
        axis_name=axis_name,
        nranks=len(ranks) if ranks else 1,
        ranks=ranks or [0],
    )
    _GROUPS[g.id] = g  # noqa: PTA402 -- bookkeeping registry, ints/ids only
    _GROUPS_CREATED += 1
    return g


def get_group(gid=0):
    return _GROUPS.get(gid) or _default_group()


def destroy_process_group(group=None):
    if group is None:
        _GROUPS.clear()
    else:
        _GROUPS.pop(group.id, None)


def _axis_live(group):
    """The axis over which this collective should compile, or None (eager)."""
    if group.axis_name is None:
        return None
    return collective_ctx.current_axis(group.axis_name)


def _eager_guard(group, opname):
    if group.nranks == 1:
        return  # degenerate world: identity
    raise RuntimeError(
        f"paddle.distributed.{opname} on a {group.nranks}-rank group was "
        f"called outside shard_map scope for axis {group.axis_name!r}. "
        "TPU-native collectives compile inside shard_map/pjit (use "
        "fleet.distributed_model / shard_map, or a world-size-1 group)."
    )


def _unary(tensor, fn, in_place=True):
    out = apply(fn, tensor) if isinstance(tensor, Tensor) else fn(tensor)
    if in_place and isinstance(tensor, Tensor):
        tensor._data = out._data
        tensor._tape_node = out._tape_node
        tensor.stop_gradient = out.stop_gradient
        return None
    return out


#: ReduceOp -> the collective the ledger records for an all_reduce
#: (PROD gathers then multiplies; AVG's pmean lowers to psum + divide)
_REDUCE_TICK_OP = {
    ReduceOp.SUM: "psum", ReduceOp.MAX: "pmax", ReduceOp.MIN: "pmin",
    ReduceOp.PROD: "all_gather", ReduceOp.AVG: "psum",
}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce (ref: communication/all_reduce.py (U))."""
    group = _resolve(group)
    _tick(_REDUCE_TICK_OP.get(op, "psum"), group, tensor)
    axis = _axis_live(group)
    if axis is None:
        _eager_guard(group, "all_reduce")
        return None

    def fn(x):
        if op == ReduceOp.SUM:
            return lax.psum(x, axis)
        if op == ReduceOp.MAX:
            return lax.pmax(x, axis)
        if op == ReduceOp.MIN:
            return lax.pmin(x, axis)
        if op == ReduceOp.PROD:
            return jnp.prod(lax.all_gather(x, axis, axis=0, tiled=False), axis=0)
        if op == ReduceOp.AVG:
            return lax.pmean(x, axis)
        raise ValueError(f"unknown ReduceOp {op}")

    return _unary(tensor, fn)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """Gather shards from every rank (ref: communication/all_gather.py (U)).

    SPMD form: returns/extends with the gathered global tensor. The reference
    fills `tensor_list` with per-rank tensors; we append per-rank slices so
    caller code written against the reference API keeps working."""
    group = _resolve(group)
    _tick("all_gather", group, tensor)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "all_gather")
        if tensor_list is not None:
            tensor_list.append(tensor)
            return None
        return tensor

    gathered = apply(lambda x: lax.all_gather(x, ax, axis=axis, tiled=False), tensor)
    if tensor_list is not None:
        # tiled=False inserts the nranks dimension at position `axis`
        from ..tensor.manipulation import unstack

        tensor_list.extend(unstack(gathered, axis=axis))
        return None
    return gathered


def all_gather_object(object_list, obj, group=None):
    group = _resolve(group)
    if group.nranks == 1:
        object_list.append(obj)
        return None
    raise RuntimeError("all_gather_object requires host-side exchange; use "
                       "jax.experimental.multihost_utils in multi-process mode")


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce-to-root == all_reduce under SPMD (every shard holds the result;
    XLA DCE drops it on non-consuming ranks)."""
    return all_reduce(tensor, op=op, group=group, sync_op=sync_op)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    """ref: communication/reduce_scatter.py (U). Output `tensor` receives this
    rank's reduced shard (psum_scatter over the axis)."""
    group = _resolve(group)
    ax = _axis_live(group)
    src = tensor_or_tensor_list
    _tick("psum_scatter", group,
          *(src if isinstance(src, (list, tuple)) else (src,)))
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat

        src = concat(list(src), axis=0)
    if ax is None:
        _eager_guard(group, "reduce_scatter")
        if isinstance(tensor, Tensor):
            tensor._data = src._data if isinstance(src, Tensor) else src
        return None
    out = apply(lambda x: lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True), src)
    if isinstance(tensor, Tensor):
        tensor._data = out._data
        tensor._tape_node = out._tape_node
        tensor.stop_gradient = out.stop_gradient
        return None
    return out


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Under SPMD a broadcast is: select the source shard on every rank."""
    group = _resolve(group)
    _tick("all_gather", group, tensor)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "broadcast")
        return None
    src_in_group = group.get_group_rank(src)
    if src_in_group < 0:
        raise ValueError(
            f"broadcast src={src} is not a member of group {group.ranks}")

    def fn(x):
        # all_gather then index the source slice: compiles to a broadcast
        return lax.all_gather(x, ax, axis=0, tiled=False)[src_in_group]

    return _unary(tensor, fn)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    group = _resolve(group)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "scatter")
        if tensor_list:
            t = tensor_list[src if src < len(tensor_list) else 0]
            tensor._data = t._data if isinstance(t, Tensor) else t
        return None
    from ..tensor.manipulation import stack

    full = stack(list(tensor_list), axis=0)

    def fn(x):
        idx = lax.axis_index(ax)
        # every rank holds the full stack (src-replicated); take own slice
        return lax.dynamic_index_in_dim(x, idx, axis=0, keepdims=False)

    out = apply(fn, full)
    tensor._data = out._data
    tensor._tape_node = out._tape_node
    tensor.stop_gradient = out.stop_gradient
    return None


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """ref: communication/all_to_all.py (U). SPMD: lax.all_to_all."""
    group = _resolve(group)
    _tick("all_to_all", group, *in_tensor_list)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "alltoall")
        out_tensor_list.extend(in_tensor_list)
        return None
    from ..tensor.manipulation import stack

    full = stack(list(in_tensor_list), axis=0)
    out = apply(
        lambda x: lax.all_to_all(x, ax, split_axis=0, concat_axis=0, tiled=False),
        full,
    )
    for i in range(group.nranks):
        out_tensor_list.append(out[i])
    return None


def alltoall_single(
    out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None,
    group=None, sync_op=True,
):
    group = _resolve(group)
    _tick("all_to_all", group, in_tensor)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "alltoall_single")
        out_tensor._data = in_tensor._data
        return None
    if in_split_sizes or out_split_sizes:
        raise NotImplementedError("uneven alltoall splits are not supported on TPU "
                                  "(XLA all_to_all requires equal splits)")
    out = apply(
        lambda x: lax.all_to_all(x, ax, split_axis=0, concat_axis=0, tiled=True),
        in_tensor,
    )
    if isinstance(out_tensor, Tensor):
        out_tensor._data = out._data
        out_tensor._tape_node = out._tape_node
        out_tensor.stop_gradient = out.stop_gradient
        return None
    return out


def shift(tensor, offset=1, group=None):
    """TPU-native p2p primitive: circular shift along the group axis via
    `lax.ppermute` — the building block pipeline/ring layers use instead of
    the reference's send_v2/recv_v2 ops (SURVEY.md §2.1 N14)."""
    group = _resolve(group)
    _tick("ppermute", group, tensor)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "shift")
        return tensor
    n = group.nranks
    perm = [(i, (i + offset) % n) for i in range(n)]
    return apply(lambda x: lax.ppermute(x, ax, perm), tensor)


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send. SPMD form: uniform-shift ppermute (dst = my_rank + k for the
    same k on every rank — the only pattern pipeline parallelism needs).
    The shifted value is buffered per (axis, offset) until the matching
    recv(); the buffer is cleared when the axis scope exits, so a send left
    unconsumed (aborted trace) cannot leak a stale tracer into a later
    program."""
    group = _resolve(group)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "send")
        return None
    offset = (dst - group.rank) % group.nranks
    _P2P_BUF.setdefault((ax, offset), []).append(shift(tensor, offset=offset, group=group))
    return None


def recv(tensor, src=0, group=None, sync_op=True):
    group = _resolve(group)
    ax = _axis_live(group)
    if ax is None:
        _eager_guard(group, "recv")
        return None
    offset = (group.rank - src) % group.nranks
    pending = _P2P_BUF.get((ax, offset))
    if not pending:
        raise RuntimeError(
            f"recv(src={src}) on axis {ax!r}: no matching send() with shift "
            f"{offset} in this SPMD program")
    out = pending.pop(0)
    tensor._data = out._data
    tensor._tape_node = out._tape_node
    tensor.stop_gradient = out.stop_gradient
    return None


class _P2PBuf(threading.local):
    """Pending sends, per thread (axis scopes are thread-local too): a send
    buffered in one thread must never satisfy — or be cleared by — another
    thread's trace."""

    def __init__(self):
        self.pending = {}

    def setdefault(self, key, default):
        return self.pending.setdefault(key, default)

    def get(self, key):
        return self.pending.get(key)

    def clear(self):
        self.pending.clear()


_P2P_BUF = _P2PBuf()
collective_ctx.register_scope_exit(_P2P_BUF.clear)
collective_ctx.register_scope_enter(_P2P_BUF.clear)


def isend(tensor, dst=0, group=None):
    send(tensor, dst=dst, group=group)
    return _DoneTask()


def irecv(tensor, src=0, group=None):
    recv(tensor, src=src, group=group)
    return _DoneTask()


class _DoneTask:
    """Collectives compile into the XLA program — by the time Python sees the
    result the op is scheduled; wait() is a no-op (reference returns a Task
    backed by a cuda event)."""

    def wait(self):
        return True

    def is_completed(self):
        return True


def wait(tensor, group=None, use_calc_stream=True):
    return None


def barrier(group=None):
    """No-op under single-controller SPMD; multi-process sync happens at
    compile/dispatch boundaries (jax.distributed coordination service)."""
    return None


# newer-paddle aliases
all_to_all = alltoall
all_to_all_single = alltoall_single


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """ref communication/gather.py: under SPMD gather == all_gather (every
    rank materializes the list; non-root ranks' copies are DCE'd)."""
    return all_gather(gather_list, tensor, group=group, sync_op=sync_op)


def _bcast_object_multiprocess(obj, src_process):
    """Ship an arbitrary picklable object from one process to all others:
    pickle → uint8 array → multihost_utils.broadcast_one_to_all (length
    first, then the payload, so shapes agree on every process)."""
    import pickle

    import jax
    import numpy as _np
    from jax.experimental import multihost_utils as mhu

    is_src = jax.process_index() == src_process
    if is_src:
        buf = _np.frombuffer(pickle.dumps(obj), dtype=_np.uint8).copy()
        n = _np.asarray(buf.shape[0], dtype=_np.int64)
    else:
        buf = None
        n = _np.zeros((), dtype=_np.int64)
    n = int(mhu.broadcast_one_to_all(n, is_source=is_src))
    if buf is None:
        buf = _np.zeros((n,), dtype=_np.uint8)
    buf = _np.asarray(mhu.broadcast_one_to_all(buf, is_source=is_src))
    return pickle.loads(buf.tobytes())


def broadcast_object_list(object_list, src=0, group=None):
    """Object broadcast. Single controller: every rank already reads the
    same host objects, so this is a no-op. Multi-process: the src process's
    list is pickled through the coordination service
    (jax.experimental.multihost_utils.broadcast_one_to_all) so every
    process ends up with identical objects."""
    _resolve(group)
    import jax

    if jax.process_count() > 1:
        object_list[:] = _bcast_object_multiprocess(list(object_list), src)
    return None


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Scatter Python objects: rank r receives in_object_list[r]. Single
    controller: every rank sees the full list and selects its slot.
    Multi-process: the full list broadcasts from src (non-src processes
    pass in_object_list=None, per the reference contract), then each
    process keeps its own slot."""
    explicit_group = group
    group = _resolve(group)
    import jax

    if jax.process_count() > 1:
        # Object collectives are PROCESS-granular; explicitly passed groups
        # are DEVICE-granular and cannot be honored here (they'd silently
        # be ignored), so refuse any non-trivial one. Exception: with one
        # device per process the granularities coincide, so a group
        # spanning every process IS unambiguously the world group.
        n = getattr(explicit_group, "nranks", 1) \
            if explicit_group is not None else 1
        world_spanning = (n == jax.process_count()
                          and jax.local_device_count() == 1)
        if n != 1 and not world_spanning:
            raise NotImplementedError(
                "scatter_object_list: object collectives are process-"
                "granular; device-level groups are not supported across "
                "processes — pass group=None (world)")
        full = _bcast_object_multiprocess(in_object_list, src)
        if not full:
            raise ValueError("src rank must provide in_object_list")
        if len(full) != jax.process_count():
            raise ValueError(
                f"scatter_object_list: len(in_object_list) ({len(full)}) "
                f"must equal world size ({jax.process_count()})")
        rank = jax.process_index()
        out_object_list.clear()
        out_object_list.append(full[rank])
        return None
    if in_object_list is None:
        raise ValueError("src rank must provide in_object_list")
    world = group.nranks if hasattr(group, "nranks") else 1
    if len(in_object_list) != world:
        raise ValueError(
            f"scatter_object_list: len(in_object_list) "
            f"({len(in_object_list)}) must equal group size ({world})")
    rank = group.rank if hasattr(group, "rank") else 0
    out_object_list.clear()
    out_object_list.append(in_object_list[rank])
    return None


def get_backend(group=None):
    """The communication backend name: XLA collectives over ICI/DCN (the
    reference returns 'NCCL'/'GLOO')."""
    return "XLA"
