"""ProcessMesh: the auto-parallel device grid.

Reference parity: python/paddle/distributed/auto_parallel/process_mesh.py (U).
There a ProcessMesh is an N-d array of *process ranks* used by the
completion/partition passes; here it is a thin, hashable description that
lowers to a `jax.sharding.Mesh` over the matching jax devices — all placement
math then rides GSPMD.
"""

from __future__ import annotations

import numpy as np


def _default_dim_names(ndim):
    return [f"d{i}" for i in range(ndim)]


class ProcessMesh:
    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh, dtype=np.int64)
        else:
            if shape is None or process_ids is None:
                raise ValueError("give either `mesh` or (`shape`,`process_ids`)")
            arr = np.asarray(process_ids, dtype=np.int64).reshape(shape)
        self._mesh = arr
        self._dim_names = list(dim_names) if dim_names else _default_dim_names(arr.ndim)
        if len(self._dim_names) != arr.ndim:
            raise ValueError(
                f"{len(self._dim_names)} dim_names for a {arr.ndim}-d mesh")
        self._jax_mesh = None

    # ---------------- reference API surface ----------------
    @property
    def shape(self):
        return list(self._mesh.shape)

    @property
    def ndim(self):
        return self._mesh.ndim

    @property
    def process_ids(self):
        return self._mesh.flatten().tolist()

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def mesh(self):
        return self._mesh

    def get_dim_size(self, dim_name):
        return self._mesh.shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, process_id):
        axis = self._dim_names.index(dim_name)
        coords = np.argwhere(self._mesh == process_id)
        if coords.size == 0:
            return -1
        return int(coords[0][axis])

    def __getitem__(self, index):
        sub = self._mesh[index]
        if sub.ndim == 0:
            sub = sub.reshape(1)
            return ProcessMesh(sub, dim_names=[self._dim_names[-1]])
        # dims consumed by integer indexing lose their names
        if isinstance(index, tuple):
            dropped = sum(1 for i in index if isinstance(i, int))
        else:
            dropped = 1 if isinstance(index, int) else 0
        return ProcessMesh(sub, dim_names=self._dim_names[dropped:])

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh, other._mesh)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    # ---------------- TPU lowering ----------------
    def jax_mesh(self):
        """The jax.sharding.Mesh this ProcessMesh denotes.

        Process ids index `jax.devices()` — on a multi-host slice those are
        global device ids, so the same ProcessMesh literal works on every
        host (SPMD single-program contract).
        """
        if self._jax_mesh is None:
            import jax
            from jax.sharding import Mesh

            devices = jax.devices()
            if len(devices) < self._mesh.size:
                # fall back to the virtual CPU platform (tests / dry runs)
                cpu = jax.devices("cpu")
                if len(cpu) >= self._mesh.size:
                    devices = cpu
                else:
                    raise RuntimeError(
                        f"ProcessMesh needs {self._mesh.size} devices, have "
                        f"{len(devices)}")
            dev_arr = np.empty(self._mesh.shape, dtype=object)
            for coord in np.ndindex(self._mesh.shape):
                dev_arr[coord] = devices[int(self._mesh[coord])]
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    @classmethod
    def from_jax(cls, jmesh):
        ids = np.vectorize(lambda d: d.id)(jmesh.devices)
        return cls(ids, dim_names=list(jmesh.axis_names))


_GLOBAL_MESH = None


def set_mesh(mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh():
    return _GLOBAL_MESH
