"""Semi-auto-parallel API: shard_tensor / reshard / shard_layer / shard_optimizer.

Reference parity: python/paddle/distributed/auto_parallel/api.py (U). The
reference routes these through dist_tensor C++ bindings plus program passes;
here every entry point is a `jax.device_put` with a `NamedSharding` — GSPMD
does propagation, partitioning, and reshard-collective insertion.

Partial semantics note: eagerly (outside jit) a `jax.Array` cannot hold a
different addend per mesh coordinate, so a Partial dist-tensor stores the
*logical total* and partial-ness as metadata; `reshard(..., Replicate())`
materializes the reduction result ("avg" divides by the partial axis size,
matching the reference's r_to_p + reduce pipeline). Inside jit, XLA tracks
true per-device partial values on its own.
"""

from __future__ import annotations

import weakref

import jax
import numpy as np

from ...core.tensor import Tensor
from .placement import Partial, Placement, Replicate, Shard, named_sharding, spec_to_placements
from .process_mesh import ProcessMesh

# id(tensor) -> (ProcessMesh, tuple(placements)); entries die with the tensor
_DIST_ATTRS: dict = {}


def _record(t, mesh, placements):
    key = id(t)
    _DIST_ATTRS[key] = (mesh, tuple(placements))  # noqa: PTA402 -- metadata only; entry dies with the tensor
    weakref.finalize(t, _DIST_ATTRS.pop, key, None)


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def get_process_mesh(t):
    """The ProcessMesh a dist tensor lives on (derived from its jax sharding
    if it was produced by sharding propagation rather than shard_tensor)."""
    rec = _DIST_ATTRS.get(id(t))
    if rec is not None:
        return rec[0]
    sh = getattr(t._data, "sharding", None)
    if sh is not None and hasattr(sh, "mesh") and sh.mesh.axis_names:
        return ProcessMesh.from_jax(sh.mesh)
    return None


def get_placements(t):
    """Per-mesh-dim placements of a dist tensor (paddle `Tensor.placements`)."""
    rec = _DIST_ATTRS.get(id(t))
    if rec is not None:
        return list(rec[1])
    sh = getattr(t._data, "sharding", None)
    if sh is None or not hasattr(sh, "spec"):
        return None
    return spec_to_placements(sh.spec, sh.mesh.axis_names, t._data.ndim)


def shard_tensor(data, mesh, placements, dtype=None, place=None, stop_gradient=None):
    """Place `data` on `mesh` according to `placements` (one per mesh dim)."""
    t = _as_tensor(data)
    if not isinstance(mesh, ProcessMesh):
        raise TypeError(f"mesh must be a ProcessMesh, got {type(mesh)}")
    if len(placements) != mesh.ndim:
        raise ValueError(
            f"{len(placements)} placements for a {mesh.ndim}-d mesh")
    for p in placements:
        if not isinstance(p, Placement):
            raise TypeError(f"placements must be Placement objects, got {p!r}")
    sharding = named_sharding(mesh, placements, t._data.ndim)
    out = Tensor(jax.device_put(t._data, sharding),
                 stop_gradient=t.stop_gradient if stop_gradient is None else stop_gradient)
    _record(out, mesh, placements)
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """Build a tensor with `fn(*args, **kwargs)` and shard it (paddle parity:
    used to materialize large params directly with a distributed layout)."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh, placements):
    """Move a dist tensor to a new placement (XLA inserts the collective)."""
    t = _as_tensor(dist_tensor)
    cur = get_placements(t) or []
    data = t._data
    partial_dims = [i for i, p in enumerate(cur) if isinstance(p, Partial)]
    if partial_dims:
        src_mesh = get_process_mesh(t)
        for i in partial_dims:
            if i < len(placements) and isinstance(placements[i], Partial):
                continue  # stays partial on this dim
            if cur[i].reduce_type == "avg":
                data = data / src_mesh.shape[i]
    sharding = named_sharding(mesh, placements, data.ndim)
    out = Tensor(jax.device_put(data, sharding), stop_gradient=t.stop_gradient)
    _record(out, mesh, placements)
    return out


def unshard_dtensor(dist_tensor):
    """Gather to a fully-replicated local tensor (paddle parity)."""
    t = _as_tensor(dist_tensor)
    mesh = get_process_mesh(t)
    if mesh is None:
        return t
    return reshard(t, mesh, [Replicate()] * mesh.ndim)


# ------------------------------------------------------------------ layers

def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None, output_fn=None):
    """Shard every parameter of `layer` onto `process_mesh`.

    shard_fn(sublayer_name, sublayer, process_mesh) may call shard_tensor on
    the sublayer's params; params it leaves alone are replicated (reference
    default). input_fn/output_fn hook the layer boundary (e.g. to shard the
    batch in and gather logits out).
    """
    if not isinstance(process_mesh, ProcessMesh):
        raise TypeError("process_mesh must be a ProcessMesh")

    def _replicate_param(p):
        if _DIST_ATTRS.get(id(p)) is None:
            placements = [Replicate()] * process_mesh.ndim
            sharding = named_sharding(process_mesh, placements, p._data.ndim)
            p._data = jax.device_put(p._data, sharding)
            _record(p, process_mesh, placements)

    for name, sub in layer.named_sublayers(include_self=True):
        if shard_fn is not None:
            shard_fn(name, sub, process_mesh)
    for _, p in layer.named_parameters():
        _replicate_param(p)

    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_parameter(param, mesh, placements):
    """In-place placement of an existing Parameter (keeps identity so the
    optimizer's id-keyed accumulators still match)."""
    sharding = named_sharding(mesh, placements, param._data.ndim)
    param._data = jax.device_put(param._data, sharding)
    _record(param, mesh, placements)
    return param


# ------------------------------------------------------------------ optimizer

class _ShardOptimizer:
    """paddle.distributed.shard_optimizer result: the wrapped optimizer, with
    accumulator state placed like its parameter (or per a custom shard_fn —
    the hook the reference uses for ZeRO-style optimizer-state sharding)."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner_opt = optimizer
        self._shard_fn = shard_fn

    def _place_state(self, p, state):
        placed = {}
        for k, v in state.items():
            if self._shard_fn is not None:
                placed[k] = self._shard_fn(k, p, v)
            elif getattr(v, "ndim", 0) == getattr(p._data, "ndim", -1) and v.shape == p._data.shape:
                placed[k] = jax.device_put(v, p._data.sharding)
            else:
                placed[k] = v
        return placed

    def _state_for(self, p):
        opt = self._inner_opt
        st = opt._accumulators.get(id(p))
        if st is None:
            st = self._place_state(p, opt._init_state(p))
            opt._accumulators[id(p)] = st
        return st

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def __setattr__(self, name, value):
        if name in ("_inner_opt", "_shard_fn"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner_opt, name, value)


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)


# ------------------------------------------------------------------ to_static

class DistModel:
    """paddle.distributed.to_static result: a compiled distributed train step.

    Reference: the static auto-parallel Engine (completion→partition→reshard
    over a Program). Here: paddle_tpu.jit.TrainStep jitted under the mesh —
    GSPMD performs all three passes during XLA compilation.
    """

    def __init__(self, layer, loader=None, loss=None, optimizer=None, strategy=None):
        self.network = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._mode = "train"
        self._step = None

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def _loss_fn(self, net, *batch):
        *inputs, label = batch
        out = net(*inputs)
        loss = self._loss(out, label)
        return loss

    def __call__(self, *batch):
        from ...jit.train_step import TrainStep

        batch = [_as_tensor(b) for b in batch]
        if self._mode == "train" and self._optimizer is not None:
            if self._step is None:
                self._step = TrainStep(self.network, self._loss_fn,
                                       self._optimizer)
            return self._step(*batch)
        *inputs, label = batch
        out = self.network(*inputs)
        return self._loss(out, label) if self._loss is not None else out


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    if isinstance(optimizer, _ShardOptimizer) is False and optimizer is not None:
        optimizer = shard_optimizer(optimizer)
    return DistModel(layer, loader, loss, optimizer, strategy)
