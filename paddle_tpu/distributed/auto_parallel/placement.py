"""Placement types and conversion to/from jax PartitionSpec.

Reference parity: paddle.distributed.{Shard,Replicate,Partial}
(python/paddle/distributed/auto_parallel/placement_type.py (U)). A placements
list has one entry per *mesh dimension*: `placements[i]` says what mesh dim i
does to the tensor (shard a tensor dim / replicate / hold partial sums).
"""

from __future__ import annotations

from jax.sharding import NamedSharding, PartitionSpec


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Shard(Placement):
    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Partial(Placement):
    """Pending-reduction placement. reduce_type: "sum" | "avg" | "max" | "min"."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))


def placements_to_spec(placements, mesh_dim_names, tensor_ndim):
    """[per-mesh-dim placements] -> PartitionSpec (per-tensor-dim axis names).

    Partial contributes no sharding at the SPMD level (the unreduced value is
    replicated per mesh coordinate); callers track partial-ness separately.
    """
    per_dim = [[] for _ in range(tensor_ndim)]
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            if pl.dim >= tensor_ndim:
                raise ValueError(
                    f"Shard(dim={pl.dim}) out of range for ndim={tensor_ndim}")
            per_dim[pl.dim].append(mesh_dim_names[mesh_dim])
    entries = []
    for axes in per_dim:
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def spec_to_placements(spec, mesh_dim_names, tensor_ndim):
    """PartitionSpec -> per-mesh-dim placements list (inverse of the above)."""
    placements = [Replicate() for _ in mesh_dim_names]
    entries = tuple(spec) if spec is not None else ()
    for tensor_dim, entry in enumerate(entries):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[list(mesh_dim_names).index(ax)] = Shard(tensor_dim)
    return placements


def named_sharding(process_mesh, placements, tensor_ndim):
    jmesh = process_mesh.jax_mesh()
    spec = placements_to_spec(placements, jmesh.axis_names, tensor_ndim)
    return NamedSharding(jmesh, spec)
