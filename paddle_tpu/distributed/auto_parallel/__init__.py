"""paddle.distributed.auto_parallel parity (semi-auto parallel API).

Reference: python/paddle/distributed/auto_parallel/ (U) — ProcessMesh,
shard_tensor with Shard/Replicate/Partial placements, reshard, shard_layer,
shard_optimizer, and the static Engine (SURVEY.md §2.2 P23, ~80k LoC of
completion/partition/reshard passes).

TPU-native design: the reference implements its own SPMD propagation
(completion pass), partitioner, and reshard pass because it must rewrite a
serialized Program. Under XLA *GSPMD is that whole pipeline*: placements
lower to a `NamedSharding` on the backing `jax.Array`, op-level propagation
is done by the compiler, and `reshard` is a `device_put` that XLA turns into
the minimal collective. `Partial` — which the reference tracks as a
first-class placement — is realized here at the API boundary (a partial
tensor materializes the unreduced addends; `reshard` to Replicate emits the
psum), since inside jit XLA manages partial values internally.
"""

from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh
from .api import (
    DistModel,
    dtensor_from_fn,
    get_placements,
    get_process_mesh,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    to_static,
    unshard_dtensor,
)

__all__ = [
    "ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
    "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
    "shard_optimizer", "to_static", "DistModel", "get_placements",
    "get_process_mesh", "unshard_dtensor",
]
