"""Distributed checkpoint with reshard-on-load.

Reference parity: python/paddle/distributed/checkpoint/ (U) —
`save_state_dict` / `load_state_dict` where each rank saves its shards with
structure metadata and loading reshards across changed meshes
(SURVEY.md §5 checkpoint/resume, §2.2 P23).

TPU-native design: orbax (tensorstore) is the storage engine — it writes
sharded jax.Arrays natively (each host writes only its addressable shards,
OCDBT format) and reshards on restore when the target sharding differs; the
reference's hand-rolled shard metadata + reshard pass collapses into
"restore with an abstract target". Plain numpy fallback keeps single-host
checkpoints dependency-light.
"""

from __future__ import annotations

import os

import numpy as np

import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_all_saves"]

# async saves in flight: orbax checkpointers whose write threads are still
# running (each holds its own thread; barriered before a new save to the
# same path, and drainable via wait_all_saves / atexit)
_pending = {}


def _drain(path=None):
    items = (list(_pending.items()) if path is None
             else [(path, _pending[path])] if path in _pending else [])
    for p, ck in items:
        ck.wait_until_finished()
        ck.close()
        _pending.pop(p, None)


def wait_all_saves():
    """Block until every in-flight async checkpoint save has committed
    (ref: the async save barrier on exit/next-save)."""
    _drain()


import atexit as _atexit

_atexit.register(wait_all_saves)


def _arrays(state_dict):
    out = {}
    for k, v in state_dict.items():
        out[k] = v._data if isinstance(v, Tensor) else v
    return out


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    """Save a (possibly sharded) state_dict to `path` (a directory).

    async_save=True (ref save_state_dict(..., async_save) (U)): the call
    returns as soon as the arrays are snapshotted — orbax's async
    checkpointer commits on a background thread while training proceeds.
    The write is barriered before any subsequent save to the same path,
    by wait_all_saves(), and at interpreter exit."""
    arrays = _arrays(state_dict)
    try:
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        # a previous in-flight save to this path must commit first (the
        # reference serializes successive async saves the same way)
        _drain(path)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "state"), arrays, force=True)
        if async_save:
            _pending[path] = ckptr
            return
        ckptr.wait_until_finished()
        ckptr.close()
        return
    except ModuleNotFoundError:
        pass
    os.makedirs(path, exist_ok=True)
    np.savez(os.path.join(path, "state.npz"),
             **{k: np.asarray(v) for k, v in arrays.items()})


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Load `path` into `state_dict` IN PLACE (reference semantics), resharding
    each array to the target tensor's current sharding."""
    targets = {k: v for k, v in state_dict.items()}
    arrays = _arrays(state_dict)
    loaded = None
    _drain(os.path.abspath(path))   # an in-flight async save must commit
    orbax_dir = os.path.join(os.path.abspath(path), "state")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        # abstract target: same shape/dtype/sharding as the live arrays —
        # orbax reshards stored shards onto it (reshard-on-load)
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=getattr(a, "sharding", None)),
            arrays)
        loaded = ckptr.restore(orbax_dir, abstract)
    else:
        npz = os.path.join(path, "state.npz")
        if not os.path.exists(npz):
            raise FileNotFoundError(f"no checkpoint found under {path}")
        with np.load(npz) as data:
            loaded = {k: data[k] for k in data.files}

    missing = [k for k in targets if k not in loaded]
    if missing:
        raise KeyError(f"checkpoint at {path} is missing keys: {missing[:5]}...")
    for k, tgt in targets.items():
        arr = loaded[k]
        if isinstance(tgt, Tensor):
            sharding = getattr(tgt._data, "sharding", None)
            if sharding is not None and not isinstance(arr, np.ndarray):
                arr = jax.device_put(arr, sharding)
            elif sharding is not None:
                arr = jax.device_put(np.asarray(arr), sharding)
            tgt._data = arr.astype(tgt._data.dtype) if arr.dtype != tgt._data.dtype else arr
        else:
            state_dict[k] = arr
    return state_dict
