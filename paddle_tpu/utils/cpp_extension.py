"""Custom native-op extension loader (ref: python/paddle/utils/cpp_extension/
(U), SURVEY.md §2.2 P29).

TPU-native shape: a custom op is a C++ shared library exposing plain C
symbols, registered as an XLA FFI custom call OR called on host via ctypes
from a jax.pure_callback. This module compiles C++ sources with the system
toolchain (g++ — no CUDA, no pybind11) and returns a ctypes handle plus a
helper to wrap host functions as differentiable paddle ops.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig


DEFAULT_FLAGS = ["-O3", "-fPIC", "-shared", "-std=c++17", "-march=native"]


def load(name, sources, extra_cxx_flags=None, extra_include_paths=None,
         build_directory=None, verbose=False, **kwargs):
    """Compile `sources` into lib<name>.so and return a ctypes.CDLL."""
    build_dir = build_directory or os.path.join(
        os.environ.get("PADDLE_TPU_EXT_DIR", os.path.expanduser("~/.cache/paddle_tpu_ext"))
    )
    os.makedirs(build_dir, exist_ok=True)
    src_key = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            src_key.update(f.read())
    tag = src_key.hexdigest()[:12]
    out = os.path.join(build_dir, f"lib{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", *DEFAULT_FLAGS]
        for inc in (extra_include_paths or []):
            cmd += ["-I", inc]
        cmd += ["-I", sysconfig.get_paths()["include"]]
        cmd += list(sources) + (extra_cxx_flags or []) + ["-o", out]
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)


def host_op(lib, fn_name, out_shape_fn, arg_dtypes=None):
    """Wrap a C symbol `void fn(const float* in, float* out, long n)`-style
    host function as a paddle op via jax.pure_callback."""
    import numpy as np
    import jax

    from ..core.op_call import apply
    from ..tensor.creation import _as_t

    cfn = getattr(lib, fn_name)

    def host_call(a):
        a = np.ascontiguousarray(a)
        out = np.empty(out_shape_fn(a.shape), a.dtype)
        cfn(
            a.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_long(a.size),
        )
        return out

    def op(x):
        x = _as_t(x)

        def f(arr):
            shape = out_shape_fn(arr.shape)
            return jax.pure_callback(
                host_call, jax.ShapeDtypeStruct(shape, arr.dtype), arr
            )

        return apply(f, x, _op_name=fn_name)

    return op


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources


class CUDAExtension(CppExtension):
    def __init__(self, *a, **k):
        raise NotImplementedError("no CUDA on the TPU build; write a Pallas kernel instead")


def setup(**kwargs):
    raise NotImplementedError("use paddle_tpu.utils.cpp_extension.load for JIT builds")
