"""paddle.utils parity (subset; ref: python/paddle/utils/ (U))."""

from . import unique_name
from . import cpp_extension
from . import dlpack


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required but not installed")


def run_check():
    """paddle.utils.run_check parity: verify the device works."""
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    print(f"paddle_tpu works on {d.platform}:{d.id} ({float(y[0,0])})")


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(fn):
        return fn

    return decorator


def require_version(min_version, max_version=None):
    """ref paddle.utils.require_version: check the installed version lies in
    [min_version, max_version]."""
    from ..version import full_version

    def _key(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = _key(full_version)
    if _key(min_version) > cur:
        raise RuntimeError(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and _key(max_version) < cur:
        raise RuntimeError(
            f"installed version {full_version} > allowed {max_version}")
    return True


def download(url, path=None, md5sum=None, method="get"):
    """Zero-egress build: resolve from a local cache only (set
    PPTPU_DATA_HOME); network download raises with guidance."""
    import os

    cache = os.environ.get("PPTPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu"))
    fname = os.path.join(cache, os.path.basename(url))
    if os.path.exists(fname):
        return fname
    raise RuntimeError(
        f"no network egress in this build: place {os.path.basename(url)!r} "
        f"under {cache} (PPTPU_DATA_HOME) to use it")
