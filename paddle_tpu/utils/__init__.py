"""paddle.utils parity (subset; ref: python/paddle/utils/ (U))."""

from . import unique_name
from . import cpp_extension


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required but not installed")


def run_check():
    """paddle.utils.run_check parity: verify the device works."""
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    print(f"paddle_tpu works on {d.platform}:{d.id} ({float(y[0,0])})")


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(fn):
        return fn

    return decorator
