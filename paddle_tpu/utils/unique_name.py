"""paddle.utils.unique_name parity."""

from __future__ import annotations

import contextlib

_COUNTERS = {}


def generate(key):
    idx = _COUNTERS.get(key, 0)
    _COUNTERS[key] = idx + 1  # noqa: PTA402 -- str-keyed int counter
    return f"{key}_{idx}"


@contextlib.contextmanager
def guard(new_generator=None):
    global _COUNTERS
    saved = _COUNTERS
    _COUNTERS = {}
    try:
        yield
    finally:
        _COUNTERS = saved


def switch(new_generator=None):
    global _COUNTERS
    _COUNTERS = {}
