"""paddle.utils.dlpack (ref: python/paddle/utils/dlpack.py (U)):
zero-copy tensor interchange via the DLPack protocol. TPU-native: jax
arrays implement `__dlpack__`/`__dlpack_device__`, so export is the
array's own capsule and import is `jnp.from_dlpack` — CPU-side interop
with torch/numpy is zero-copy; device arrays transfer through the
producer's stream semantics."""

from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a Tensor for DLPack consumers. Returns the underlying array,
    which carries `__dlpack__`/`__dlpack_device__` — the modern protocol
    form every consumer (torch/numpy/jax `from_dlpack`) accepts, without
    the consumed-once hazard of a bare capsule."""
    if isinstance(x, Tensor):
        x = x._data
    return x


class _CapsuleShim:
    """Adapter for LEGACY bare capsules (e.g. torch.utils.dlpack.to_dlpack
    output): presents the protocol surface jax's from_dlpack requires. A
    capsule names no device, so this assumes kDLCPU — which is where
    legacy-capsule producers in this environment (cpu torch) live."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # (kDLCPU, device 0)


def from_dlpack(dlpack):
    """Import a DLPack-protocol object (torch tensor, numpy array, jax
    array, ...) or a legacy CPU capsule as a paddle Tensor."""
    import jax.numpy as jnp

    if not hasattr(dlpack, "__dlpack__"):
        dlpack = _CapsuleShim(dlpack)
    return Tensor(jnp.from_dlpack(dlpack))
