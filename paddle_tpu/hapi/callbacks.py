"""hapi callbacks (ref: python/paddle/hapi/callbacks.py (U))."""

from __future__ import annotations

import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"step {step}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch + 1} done ({time.time() - self._t0:.1f}s) - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Eval - {items}")


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.4f}"
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoint"

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(cur)
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class ObservabilityCallback(Callback):
    """Publishes the fit/eval loop into ``paddle_tpu.observability``:
    epoch begin/end timeline events, per-batch loss/lr gauges, and a
    train-step counter — so a dashboard scraping
    ``observability.render_prometheus()`` (or the ``python -m
    paddle_tpu.observability`` CLI) sees training progress live, next to
    the jit/serving/dataloader metrics the subsystems publish on their
    own.  Purely additive: Model.fit already records step-time/ips
    histograms unconditionally."""

    def __init__(self, prefix="hapi"):
        super().__init__()
        from ..observability import events, metrics

        self._events = events
        self.prefix = prefix
        self._loss = metrics.gauge(f"{prefix}.loss",
                                   "last training-batch loss")
        self._lr = metrics.gauge(f"{prefix}.lr", "current learning rate")
        self._steps = metrics.counter(f"{prefix}.train_batches",
                                      "train batches seen by Model.fit")
        self._eval_loss = metrics.gauge(f"{prefix}.eval_loss",
                                        "last evaluation loss")

    def on_train_begin(self, logs=None):
        self._events.instant(f"{self.prefix}.train_begin", cat="hapi",
                             epochs=self.params.get("epochs"))

    def on_train_end(self, logs=None):
        self._events.instant(f"{self.prefix}.train_end", cat="hapi")

    def on_epoch_begin(self, epoch, logs=None):
        self._events.begin(f"{self.prefix}.epoch", cat="hapi",
                           epoch=epoch)

    def on_epoch_end(self, epoch, logs=None):
        self._events.end(f"{self.prefix}.epoch", cat="hapi", epoch=epoch)

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self._steps.inc()
        loss = logs.get("loss")
        if isinstance(loss, (list, tuple)) and loss:
            loss = loss[0]
        if isinstance(loss, (int, float)):
            self._loss.set(loss)
        lr = logs.get("lr")
        if isinstance(lr, (int, float)):
            self._lr.set(lr)

    def on_eval_begin(self, logs=None):
        self._events.begin(f"{self.prefix}.eval", cat="hapi")

    def on_eval_end(self, logs=None):
        logs = logs or {}
        loss = logs.get("loss")
        if isinstance(loss, (int, float)):
            self._eval_loss.set(loss)
        self._events.end(f"{self.prefix}.eval", cat="hapi")


class VisualDL(Callback):
    """VisualDL is an ecosystem package; on the TPU build scalars are logged
    as TSV so any dashboard can ingest them."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None

    def on_train_begin(self, logs=None):
        self._f = open(os.path.join(self.log_dir, "scalars.tsv"), "a")

    def on_train_batch_end(self, step, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float)):
                self._f.write(f"{time.time()}\t{step}\t{k}\t{v}\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
