"""paddle.summary parity (ref: python/paddle/hapi/model_summary.py (U))."""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = sum(p.size for p in layer._parameters.values() if p is not None)
        if n_params == 0 and layer._sub_layers:
            continue
        total_params += n_params
        trainable_params += sum(
            p.size for p in layer._parameters.values() if p is not None and p.trainable
        )
        rows.append((name or type(layer).__name__, type(layer).__name__, n_params))
    width = max((len(r[0]) for r in rows), default=10) + 2
    lines = [f"{'Layer':<{width}}{'Type':<28}{'Params':>12}", "-" * (width + 40)]
    for name, ty, n in rows:
        lines.append(f"{name:<{width}}{ty:<28}{n:>12,}")
    lines.append("-" * (width + 40))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}
