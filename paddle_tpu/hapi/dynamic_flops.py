"""paddle.flops parity (ref: python/paddle/hapi/dynamic_flops.py (U)) —
analytic FLOPs count for the common layer types."""

from __future__ import annotations

import numpy as np


def flops(net, input_size, custom_ops=None, print_detail=False):
    import paddle_tpu as paddle
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd

    total = [0]
    hooks = []

    def count(layer, inputs, output):
        from ..core.tensor import Tensor

        x = inputs[0] if inputs else None
        if custom_ops and type(layer) in custom_ops:
            total[0] += custom_ops[type(layer)](layer, x, output)
            return
        if isinstance(layer, Linear):
            total[0] += 2 * layer.weight.size * (x.size // x.shape[-1] if x is not None else 1)
        elif isinstance(layer, _ConvNd):
            if isinstance(output, Tensor):
                out_el = output.size
                total[0] += 2 * out_el * layer.weight.size // layer.weight.shape[0]

    for l in net.sublayers(include_self=True):
        hooks.append(l.register_forward_post_hook(count))
    x = paddle.randn(list(input_size))
    net.eval()
    with paddle.no_grad():
        net(x)
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]
