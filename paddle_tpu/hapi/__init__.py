from .model import Model
from .model_summary import summary
from .dynamic_flops import flops
from . import callbacks
