"""paddle.Model high-level API (ref: python/paddle/hapi/model.py (U)).

fit/evaluate/predict over the dygraph core. The train loop runs through
jit.TrainStep BY DEFAULT (r5, measured: BERT-base fit() on one chip is
193.7 seq/s jitted vs 0.7 eager — 277x; AB_HAPI_FIT.json), with a loud
one-time fallback to eager when the forward cannot trace — pass
`prepare(..., jit=False)` to force the reference's eager-per-batch
behavior.
"""

from __future__ import annotations

import time

import numpy as np

from ..core.tensor import Tensor
from ..core import tape as _tape
from ..observability import metrics as _obs_metrics
from .callbacks import Callback, ProgBarLogger, ModelCheckpoint, LRScheduler as LRCallback
from ..metric import Metric

_FIT_STEP_SECONDS = _obs_metrics.histogram(
    "hapi.step_seconds", "Model.fit wall seconds per train batch")
_FIT_IPS = _obs_metrics.histogram(
    "hapi.ips", "Model.fit samples per second, by train batch")
_EVAL_BATCH_SECONDS = _obs_metrics.histogram(
    "hapi.eval_batch_seconds", "Model.evaluate wall seconds per batch")


def _batch_rows(inputs):
    """Leading-dim sample count of the first array-like input (None when
    the batch carries no shaped leaf)."""
    for x in inputs:
        shape = getattr(x, "shape", None)
        if shape:
            return int(shape[0])
    return None


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._train_step = None
        self._train_step_labels = None
        self._use_jit = False

    # -------------- setup --------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None, jit=True):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        self._use_jit = jit
        if jit and optimizer is not None and loss is not None:
            self._build_train_step(n_labels=1)
        return self

    def _build_train_step(self, n_labels):
        """Compile the train step for a known inputs/labels split. The
        label count is baked into the traced loss_fn (ADVICE r5: `*xs,
        y = batch` fed l1 into the network and scored against l2 only
        when two labels were passed), so a batch with a different number
        of labels rebuilds the step instead of silently mis-splitting."""
        from ..jit.train_step import TrainStep

        loss_layer = self._loss
        # with metrics, the compiled step also returns the network
        # outputs (aux) so the jit path reports the same per-batch
        # metrics as eager (ref Model.fit always updates train metrics);
        # without metrics, no aux — don't materialize outputs for nothing
        with_aux = bool(self._metrics)

        def loss_fn(net, *batch):
            xs, ys = batch[:len(batch) - n_labels], batch[len(batch) - n_labels:]
            out = net(*xs)
            l = loss_layer(out, *ys)
            return (l, out) if with_aux else l

        self._train_step = TrainStep(self.network, loss_fn,
                                     self._optimizer, has_aux=with_aux)
        self._train_step_labels = n_labels

    # -------------- steps --------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        use_jit = (self._use_jit and update and labels
                   and self._train_step is not None)
        if use_jit:
            if self._train_step_labels != len(labels):
                self._build_train_step(n_labels=len(labels))
            try:
                if self._train_step.has_aux:
                    loss, outs = self._train_step(*inputs, *labels)
                    self._update_metrics(outs, labels)
                else:
                    loss = self._train_step(*inputs, *labels)
                self._optimizer._lr_step()
                return [float(loss)]
            except Exception as e:
                import jax

                # genuine NotImplementedError bugs from a user forward
                # must surface, not downgrade fit() to the eager loop
                # (ADVICE r5) — only jax's tracer-leak errors fall back
                trace_errs = (jax.errors.TracerBoolConversionError,
                              jax.errors.ConcretizationTypeError,
                              jax.errors.TracerArrayConversionError,
                              jax.errors.TracerIntegerConversionError)
                if not isinstance(e, trace_errs) \
                        or self._optimizer._step_count > 0:
                    raise
                # jit-by-default: a forward that cannot trace falls back
                # to the reference's eager-per-batch loop, ONCE, loudly
                import warnings

                warnings.warn(
                    "Model.fit: the network's forward cannot be traced "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "eager per-batch loop — pass prepare(..., jit=False) "
                    "to silence, or make the forward traceable for the "
                    "compiled path (~100x faster on TPU)")
                self._train_step = None
                self._use_jit = False
        outs = self.network(*[_as_tensor(x) for x in inputs])
        loss = self._loss(outs, *[_as_tensor(y) for y in labels]) if self._loss else outs
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
            self._optimizer._lr_step()
        self._update_metrics(outs, labels)
        return [float(loss)]

    def _update_metrics(self, outs, labels):
        if not self._metrics:
            return
        with _tape.no_grad():
            lbl = [_as_tensor(y) for y in labels]
            for m in self._metrics:
                corr = m.compute(outs, *lbl)
                # base Metric.compute passes through its args as a tuple
                # (Precision/Recall); the ref hapi unpacks compute outputs
                if isinstance(corr, (tuple, list)):
                    m.update(*corr)
                else:
                    m.update(corr)

    def _metric_logs(self, logs):
        for m in self._metrics:
            name = m.name()
            res = m.accumulate()
            if isinstance(name, list):
                for n, r in zip(name, res if isinstance(res, list) else [res]):
                    logs[n] = r
            else:
                logs[name] = res
        return logs

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        with _tape.no_grad():
            outs = self.network(*[_as_tensor(x) for x in inputs])
            metrics_out = []
            loss_val = None
            if self._loss is not None and labels:
                loss_val = float(self._loss(outs, *[_as_tensor(y) for y in labels]))
            for m in self._metrics:
                corr = m.compute(outs, *[_as_tensor(y) for y in labels])
                if isinstance(corr, (tuple, list)):
                    metrics_out.append(m.update(*corr))
                else:
                    metrics_out.append(m.update(corr))
        return loss_val, metrics_out

    def predict_batch(self, inputs):
        self.network.eval()
        with _tape.no_grad():
            outs = self.network(*[_as_tensor(x) for x in _to_list(inputs)])
        return [o.numpy() for o in _to_list(outs)]

    # -------------- loops --------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = _as_loader(train_data, batch_size, shuffle, drop_last, num_workers)
        eval_loader = _as_loader(eval_data, batch_size, False, False, num_workers) if eval_data is not None else None

        cbs = [ProgBarLogger(log_freq, verbose=verbose), LRCallback()]
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cbs += list(callbacks or [])
        for cb in cbs:
            cb.set_model(self)
            cb.set_params({"epochs": epochs, "steps": _safe_len(loader), "verbose": verbose})

        self.stop_training = False
        for cb in cbs:
            cb.on_train_begin()
        step_count = 0
        for epoch in range(epochs):
            if hasattr(loader, "batch_sampler") and hasattr(loader.batch_sampler, "set_epoch"):
                loader.batch_sampler.set_epoch(epoch)
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            logs = {}
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                for cb in cbs:
                    cb.on_train_batch_begin(step)
                ins, lbls = _split_batch(batch)
                bt0 = time.perf_counter()
                losses = self.train_batch(ins, lbls)
                bdt = time.perf_counter() - bt0
                _FIT_STEP_SECONDS.observe(bdt)
                rows = _batch_rows(ins)
                if rows and bdt > 0:
                    _FIT_IPS.observe(rows / bdt)
                logs = {"loss": losses}
                logs["lr"] = self._optimizer.get_lr()
                self._metric_logs(logs)
                for cb in cbs:
                    cb.on_train_batch_end(step, logs)
                step_count += 1
                if num_iters is not None and step_count >= num_iters:
                    break
            for cb in cbs:
                cb.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self._run_eval(eval_loader, cbs)
            if self.stop_training or (num_iters is not None and step_count >= num_iters):
                break
        for cb in cbs:
            cb.on_train_end(logs)

    def _run_eval(self, loader, cbs):
        for cb in cbs:
            cb.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            ins, lbls = _split_batch(batch)
            bt0 = time.perf_counter()
            loss, _ = self.eval_batch(ins, lbls)
            _EVAL_BATCH_SECONDS.observe(time.perf_counter() - bt0)
            if loss is not None:
                losses.append(loss)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        self._metric_logs(logs)
        for cb in cbs:
            cb.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        cbs = list(callbacks or [])
        for cb in cbs:
            cb.set_model(self)
        return self._run_eval(loader, cbs)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = _split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # -------------- persistence --------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave

        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load as fload

        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _split_batch(batch):
    if isinstance(batch, (list, tuple)) and len(batch) >= 2:
        return list(batch[:-1]), [batch[-1]]
    return _to_list(batch), []


def _safe_len(loader):
    try:
        return len(loader)
    except TypeError:
        return None


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    from ..io import DataLoader, Dataset

    if data is None:
        return None
    if isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    return data  # assume iterable of batches
