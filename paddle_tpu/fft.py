"""paddle.fft parity over jnp.fft (ref: python/paddle/fft.py (U))."""

from __future__ import annotations

import jax.numpy as jnp

from .core.op_call import apply
from .tensor.creation import _as_t


def _mk(fn_name, jfn):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: jfn(a, n=n, axis=axis, norm=norm), _as_t(x), _op_name=fn_name)

    f.__name__ = fn_name
    return f


fft = _mk("fft", jnp.fft.fft)
ifft = _mk("ifft", jnp.fft.ifft)
rfft = _mk("rfft", jnp.fft.rfft)
irfft = _mk("irfft", jnp.fft.irfft)
hfft = _mk("hfft", jnp.fft.hfft)
ihfft = _mk("ihfft", jnp.fft.ihfft)


def _mk_n(fn_name, jfn):
    def f(x, s=None, axes=None, norm="backward", name=None):
        ax = tuple(axes) if axes is not None else None
        return apply(lambda a: jfn(a, s=s, axes=ax, norm=norm), _as_t(x), _op_name=fn_name)

    f.__name__ = fn_name
    return f


fft2 = _mk_n("fft2", jnp.fft.fft2)
ifft2 = _mk_n("ifft2", jnp.fft.ifft2)
rfft2 = _mk_n("rfft2", jnp.fft.rfft2)
irfft2 = _mk_n("irfft2", jnp.fft.irfft2)
fftn = _mk_n("fftn", jnp.fft.fftn)
ifftn = _mk_n("ifftn", jnp.fft.ifftn)
rfftn = _mk_n("rfftn", jnp.fft.rfftn)
irfftn = _mk_n("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), _as_t(x))


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), _as_t(x))
