"""paddle.text parity.

Dataset downloads (Imdb/Imikolov/Conll05st/…) need network access — out of
scope in a zero-egress build (full NLP models live in paddle_tpu.models).
The in-repo compute op, `viterbi_decode` / `ViterbiDecoder` (ref:
python/paddle/text/viterbi_decode.py (U)), ships here TPU-native: the
dynamic-programming recursion is a `lax.scan` over the sequence axis so the
whole decode jits as one program with static shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..tensor.creation import _as_t

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(potentials, trans, lengths, include_bos_eos_tag):
    """potentials [B, T, N], trans [N, N], lengths [B] -> (scores [B],
    paths [B, T])."""
    b, t, n = potentials.shape
    if include_bos_eos_tag:
        # reference convention: the LAST tag (n-1) is the start/BOS tag and
        # the second-to-last (n-2) is the stop/EOS tag
        alpha0 = potentials[:, 0] + trans[n - 1][None, :]
    else:
        alpha0 = potentials[:, 0]

    def step(carry, xs):
        alpha, t_idx = carry
        emit = xs  # [B, N]
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, N]
        alpha_new = jnp.max(scores, axis=1) + emit        # [B, N]
        # masked steps (past each sequence's length) carry alpha through
        active = (t_idx < lengths)[:, None]
        alpha_new = jnp.where(active, alpha_new, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(n)[None, :])
        return (alpha_new, t_idx + 1), best_prev

    (alpha, _), backptrs = lax.scan(
        step, (alpha0, jnp.ones((), jnp.int32)),
        jnp.moveaxis(potentials[:, 1:], 1, 0))            # [T-1, B, N]

    if include_bos_eos_tag:
        alpha = alpha + trans[:, n - 2][None, :]

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]

    def back(tag, ptr):
        # ptr[i] maps tag_{i+1} -> tag_i; emit tag_i at position i
        prev = jnp.take_along_axis(ptr, tag[:, None], axis=1)[:, 0]
        prev = prev.astype(jnp.int32)
        return prev, prev

    _, path_rev = lax.scan(back, last_tag, backptrs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1),
                             last_tag[:, None]], axis=1)  # [B, T]
    # mask out positions beyond each length with the last valid tag
    idx = jnp.arange(t)[None, :]
    valid = idx < lengths[:, None]
    last_valid = jnp.take_along_axis(paths, (lengths - 1)[:, None], axis=1)
    paths = jnp.where(valid, paths, last_valid)
    return scores, paths


@functools.partial(jax.jit, static_argnums=(3,))
def _viterbi_jit(p, tr, ln, include_bos_eos_tag):
    return _viterbi(p, tr, ln.astype(jnp.int32), include_bos_eos_tag)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """ref paddle.text.viterbi_decode: returns (scores, paths)."""
    pot = _as_t(potentials)
    trans = _as_t(transition_params)
    lens = _as_t(lengths)
    scores, paths = _viterbi_jit(pot._data, trans._data, lens._data,
                                 bool(include_bos_eos_tag))
    return Tensor(scores), Tensor(paths)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = _as_t(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
