"""paddle.text stub — dataset downloads need network; the TPU build keeps the
namespace for import compatibility (full NLP models live in paddle_tpu.models)."""

__all__ = []
