"""paddle.hub parity (ref: python/paddle/hapi/hub.py (U): load/list/help over
github/gitee/local repos exposing an hubconf.py).

Zero-egress build: only `source='local'` works — a directory containing
`hubconf.py` whose public callables are the hub entry points. Remote sources
raise with guidance instead of silently hanging on a network that isn't
there."""

from __future__ import annotations

import importlib.util
import os
import sys

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _require_local(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network egress, which this build "
            "does not have; clone the repo and use source='local'")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    _require_local(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _require_local(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entry point {model!r} in {repo_dir}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _require_local(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entry point {model!r} in {repo_dir}")
    return fn(**kwargs)
