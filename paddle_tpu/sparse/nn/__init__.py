"""paddle.sparse.nn parity (ref: python/paddle/sparse/nn/layer/ (U):
Conv3D/SubmConv3D/MaxPool3D/BatchNorm/ReLU over sparse COO tensors).

Layers hold dense Parameters (weight [*k, Cin, Cout]); the sparse geometry
work happens in sparse/conv.py's rulebook (see its docstring for the
TPU-native design)."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Parameter
from ...nn.layer.layers import Layer
from ...nn.initializer import Normal
from . import functional as F_sp
from ..conv import _tupleize


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, subm,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        if groups != 1:
            raise NotImplementedError("sparse conv groups != 1")
        self._nd = nd
        self._subm = subm
        self._stride = _tupleize(stride, nd)
        self._padding = _tupleize(padding, nd)
        self._dilation = _tupleize(dilation, nd)
        k = _tupleize(kernel_size, nd)
        fan_in = in_channels * int(np.prod(k))
        std = 1.0 / max(fan_in, 1) ** 0.5
        init = weight_attr if callable(weight_attr) else Normal(0.0, std)
        self.weight = self.create_parameter(
            shape=list(k) + [in_channels, out_channels], attr=init)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        fn = {
            (2, False): F_sp.conv2d, (2, True): F_sp.subm_conv2d,
            (3, False): F_sp.conv3d, (3, True): F_sp.subm_conv3d,
        }[(self._nd, self._subm)]
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation)


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=3,
                         subm=False, **kw)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=3,
                         subm=True, **kw)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=2,
                         subm=False, **kw)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=2,
                         subm=True, **kw)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding

    def forward(self, x):
        return F_sp.max_pool3d(x, self._k, self._s, self._p)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding

    def forward(self, x):
        return F_sp.avg_pool3d(x, self._k, self._s, self._p)


class ReLU(Layer):
    def forward(self, x):
        return F_sp.relu(x)


class BatchNorm(Layer):
    """Per-channel batchnorm over the stored values (the reference's sparse
    BatchNorm normalizes the [nse, C] value rows)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn.layer.norm import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        from .. import SparseCooTensor, sparse_coo_tensor

        if not isinstance(x, SparseCooTensor):
            return self._bn(x)
        new_vals = self._bn(x.values())
        return sparse_coo_tensor(x.indices(), new_vals, x.shape)


functional = F_sp

__all__ = [
    "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
    "MaxPool3D", "AvgPool3D", "ReLU", "BatchNorm", "functional",
]
