"""paddle.sparse.nn.functional parity (ref: python/paddle/sparse/nn/
functional/ (U)): conv/pool entry points over SparseCooTensor plus the
activation re-exports."""

from ..conv import (
    conv2d,
    conv3d,
    subm_conv2d,
    subm_conv3d,
    max_pool3d,
    avg_pool3d,
)


def relu(x, name=None):
    from .. import relu as _relu

    return _relu(x)


__all__ = [
    "conv2d", "conv3d", "subm_conv2d", "subm_conv3d",
    "max_pool3d", "avg_pool3d", "relu",
]
