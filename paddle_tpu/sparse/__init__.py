"""paddle.sparse parity (ref: python/paddle/sparse/ (U), SURVEY.md §2.1 N26).

TPU-native: COO tensors wrap jax.experimental.sparse.BCOO and stay sparse
through matmul/add/elementwise — XLA lowers BCOO contractions to
gather/segment-sum, the TPU-friendly form of the reference's cuSPARSE
kernels. Dense interop happens only at explicit `.to_dense()` (or when a
dense-only op touches the tensor through the Tensor fallback)."""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..tensor.creation import _as_t


class SparseCooTensor(Tensor):
    """COO sparse tensor. Holds a BCOO; the dense view (used when a plain
    Tensor op touches it) is built lazily so sparse pipelines never
    materialize it."""

    def __init__(self, indices, values, shape):
        idx = _as_t(indices)._data
        vals_t = _as_t(values)
        bcoo = jsparse.BCOO((vals_t._data, idx.T.astype(jnp.int32)),
                            shape=tuple(int(s) for s in shape))
        self.bcoo = bcoo
        self._dense_cache = None
        # keep the live values Tensor when it's on the tape, so
        # out.values().sum().backward() differentiates through sparse ops
        # (a fresh Tensor(bcoo.data) would be disconnected)
        self._values_t = vals_t if not vals_t.stop_gradient else None
        _init_tensor_slots(self)

    # -------------------------------------------------- lazy dense interop
    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self.bcoo.todense()
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        # a direct dense assignment (in-place mutators, device placement)
        # must keep the BCOO authoritative too, or sparse ops and the dense
        # view would silently disagree
        self._dense_cache = v
        self._values_t = None  # mutation invalidates the tracked values view
        if v is not None and getattr(self, "bcoo", None) is not None:
            import jax

            if isinstance(v, jax.core.Tracer):
                # under jit, nse cannot be derived from concrete values; use
                # the full-size static bound so the rebuild stays traceable.
                # NOTE: this allocates dense-sized index/value buffers — a
                # correct fallback for small tensors, but it defeats sparsity
                # for large ones; avoid dense in-place assignment to big
                # SparseCooTensors inside jit
                self.bcoo = jsparse.BCOO.fromdense(v, nse=int(v.size))
            else:
                self.bcoo = jsparse.BCOO.fromdense(v)

    @property
    def shape(self):
        return list(self.bcoo.shape)

    @property
    def dtype(self):
        return self.bcoo.data.dtype

    # ------------------------------------------------------------ sparse API
    def indices(self):
        # int32, not the reference's int64: with jax_enable_x64 off the
        # framework has no int64 arrays at all (int64 inputs truncate)
        return Tensor(self.bcoo.indices.T)

    def values(self):
        if getattr(self, "_values_t", None) is not None:
            return self._values_t
        return Tensor(self.bcoo.data)

    def nnz(self):
        return int(self.bcoo.nse)

    def to_dense(self):
        return Tensor(self._data)

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def coalesce(self):
        # static nse bound: traceable under jit (duplicates become padding)
        return _wrap(self.bcoo.sum_duplicates(nse=self.bcoo.nse))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def _init_tensor_slots(t):
    """Fill the base Tensor slots without touching _data (lazy property).
    Mirrors Tensor.__init__ defaults (trainable = not stop_gradient)."""
    t.grad = None
    t.stop_gradient = True
    t._tape_node = None
    t.name = None
    t.persistable = False
    t.trainable = False


def _wrap(bcoo):
    t = SparseCooTensor.__new__(SparseCooTensor)
    t.bcoo = bcoo
    t._dense_cache = None
    t._values_t = None
    _init_tensor_slots(t)
    return t


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = _as_t(indices).numpy()
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    import numpy as np

    crows_np = _as_t(crows).numpy()
    cols_np = _as_t(cols).numpy()
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return SparseCooTensor(indices, values, shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def matmul(x, y, name=None):
    """Sparse @ dense — BCOO dot_general, no densification of the sparse
    operand."""
    if isinstance(x, SparseCooTensor):
        rhs = (y.bcoo.todense() if isinstance(y, SparseCooTensor)
               else _as_t(y)._data)
        if rhs.ndim > 2:
            # bcoo_dot_general puts lhs free dims before rhs batch dims —
            # a silently transposed layout; refuse rather than mislead
            raise NotImplementedError(
                "sparse matmul supports a 1-D or 2-D dense rhs; "
                "densify with .to_dense() for batched matmul")
        n = x.bcoo.ndim
        out = jsparse.bcoo_dot_general(
            x.bcoo, rhs,
            dimension_numbers=(((n - 1,), (0,)), ((), ())))
        return Tensor(out)
    from ..tensor.math import matmul as dense_matmul

    if isinstance(y, SparseCooTensor):
        return dense_matmul(x, y.to_dense())
    return dense_matmul(x, y)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if tuple(x.bcoo.shape) != tuple(y.bcoo.shape):
            raise ValueError(
                f"sparse add shape mismatch: {x.shape} vs {y.shape}")
        import jax as _jax

        tracked = (not x.values().stop_gradient) or \
            (not y.values().stop_gradient)
        if tracked and not isinstance(x.bcoo.indices, _jax.core.Tracer) \
                and not isinstance(y.bcoo.indices, _jax.core.Tracer):
            # grad-aware path: merged pattern computed host-side from the
            # concrete indices, values merged by a differentiable
            # scatter-add (residual adds in sparse conv nets)
            import numpy as np
            from ..core.op_call import apply

            ia = np.asarray(x.bcoo.indices)
            ib = np.asarray(y.bcoo.indices)
            alli = np.concatenate([ia, ib])
            key = np.zeros(len(alli), np.int64)
            for ax, size in enumerate(x.bcoo.shape[:alli.shape[1]]):
                key = key * int(size) + alli[:, ax].astype(np.int64)
            uniq, first, inv = np.unique(key, return_index=True,
                                         return_inverse=True)
            out_idx = alli[first]
            m = len(uniq)

            def f(va, vb):
                allv = jnp.concatenate([va, vb])
                return jnp.zeros((m,) + allv.shape[1:], allv.dtype) \
                    .at[jnp.asarray(inv)].add(allv)

            vals = apply(f, x.values(), y.values(), _op_name="sparse_add")
            return sparse_coo_tensor(Tensor(jnp.asarray(out_idx.T)), vals,
                                     list(x.bcoo.shape))
        # concatenate entries then coalesce: exact sparse add, stays sparse
        # (static nse bound keeps this traceable under jit)
        data = jnp.concatenate([x.bcoo.data, y.bcoo.data])
        idx = jnp.concatenate([x.bcoo.indices, y.bcoo.indices])
        merged = jsparse.BCOO((data, idx), shape=x.bcoo.shape)
        return _wrap(merged.sum_duplicates(nse=x.bcoo.nse + y.bcoo.nse))
    a = x.to_dense() if isinstance(x, SparseCooTensor) else _as_t(x)
    b = y.to_dense() if isinstance(y, SparseCooTensor) else _as_t(y)
    from ..tensor.math import add as dense_add

    return dense_add(a, b)


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if tuple(x.bcoo.shape) != tuple(y.bcoo.shape):
            raise ValueError(
                f"sparse multiply shape mismatch: {x.shape} vs {y.shape}")
        # elementwise product at the index intersection — sparse in,
        # sparse out (the reference keeps sparse*sparse sparse)
        return _wrap(jsparse.bcoo_multiply_sparse(x.bcoo, y.bcoo))
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        yt = _as_t(y)._data
        if yt.ndim == 0:  # scalar: scale values, stay sparse
            if not x.values().stop_gradient:
                from ..core.op_call import apply

                vals = apply(lambda v: v * yt, x.values(),
                             _op_name="sparse_scale")
                return sparse_coo_tensor(x.indices(), vals,
                                         list(x.bcoo.shape))
            return _wrap(jsparse.BCOO((x.bcoo.data * yt, x.bcoo.indices),
                                      shape=x.bcoo.shape))
    a = x.to_dense() if isinstance(x, SparseCooTensor) else _as_t(x)
    b = y.to_dense() if isinstance(y, SparseCooTensor) else _as_t(y)
    from ..tensor.math import multiply as dense_mul

    return dense_mul(a, b)


def _unary_on_values(fn, dense_name):
    """Zero-preserving unary op applied to the stored values only; dense
    tensors delegate to the existing paddle op (AMP-aware op names)."""

    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            if not x.values().stop_gradient:
                # tape-tracked values (e.g. after sparse conv): route the
                # value map through apply so gradients keep flowing
                from ..core.op_call import apply

                vals = apply(fn, x.values(), _op_name=dense_name)
                return sparse_coo_tensor(x.indices(), vals,
                                         list(x.bcoo.shape))
            return _wrap(jsparse.BCOO((fn(x.bcoo.data), x.bcoo.indices),
                                      shape=x.bcoo.shape))
        if dense_name == "relu":
            from ..nn import functional as F

            return F.relu(x)
        from .. import tensor as dense_ops

        return getattr(dense_ops, dense_name)(x)

    return op


relu = _unary_on_values(lambda v: jnp.maximum(v, 0), "relu")
abs = _unary_on_values(jnp.abs, "abs")
sin = _unary_on_values(jnp.sin, "sin")
tanh = _unary_on_values(jnp.tanh, "tanh")
sqrt = _unary_on_values(jnp.sqrt, "sqrt")
neg = _unary_on_values(jnp.negative, "neg")
expm1 = _unary_on_values(jnp.expm1, "expm1")


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense sampled at `mask`'s sparsity pattern (ref
    masked_matmul / SDDMM): compute only the entries the mask keeps."""
    if not isinstance(mask, SparseCooTensor):
        raise TypeError("masked_matmul mask must be a SparseCooTensor")
    if mask.bcoo.ndim != 2:
        raise TypeError("masked_matmul supports 2-D operands only")
    a = _as_t(x)._data
    b = _as_t(y)._data
    idx = mask.bcoo.indices  # [nse, 2]
    rows = a[idx[:, 0]]           # [nse, K]
    cols = b[:, idx[:, 1]].T      # [nse, K]
    vals = jnp.sum(rows * cols, axis=-1)
    return _wrap(jsparse.BCOO((vals, idx), shape=mask.bcoo.shape))


from . import nn  # noqa: E402  (layer surface: Conv3D/SubmConv3D/pool/BN)

__all__ = [
    "SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
    "matmul", "masked_matmul", "add", "multiply", "is_same_shape",
    "relu", "abs", "sin", "tanh", "sqrt", "neg", "expm1", "nn",
]
