"""paddle.sparse parity (minimal; ref: python/paddle/sparse/ (U),
SURVEY.md §2.1 N26 — low priority on TPU: XLA has no sparse codegen, so COO
ops are expressed densely via scatter/gather; jax.experimental.sparse (BCOO)
backs matmul)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..tensor.creation import _as_t


class SparseCooTensor(Tensor):
    __slots__ = ("indices_", "values_", "dense_shape")

    def __init__(self, indices, values, shape):
        from jax.experimental import sparse as jsparse

        self.indices_ = _as_t(indices)
        self.values_ = _as_t(values)
        self.dense_shape = list(shape)
        bcoo = jsparse.BCOO((self.values_._data, self.indices_._data.T), shape=tuple(shape))
        super().__init__(bcoo.todense())

    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._data)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    if shape is None:
        idx = _as_t(indices).numpy()
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    import numpy as np

    crows_np = _as_t(crows).numpy()
    cols_np = _as_t(cols).numpy()
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return SparseCooTensor(indices, values, shape)


def matmul(x, y, name=None):
    from ..tensor.math import matmul as dense_matmul

    return dense_matmul(x.to_dense() if isinstance(x, SparseCooTensor) else x,
                        y.to_dense() if isinstance(y, SparseCooTensor) else y)


def masked_matmul(x, y, mask, name=None):
    raise NotImplementedError("masked sparse matmul is not supported on the TPU build")
