"""Sparse convolution / pooling over COO tensors.

Reference parity: paddle/phi/kernels/sparse/ conv3d + pool kernels and the
python/paddle/sparse/nn layer surface (SURVEY.md §2.1 N26). The reference
implements scatter-gather CUDA kernels; the TPU-native design is the
"rulebook" formulation the spconv family uses, mapped onto XLA primitives:

  1. build, on host from the CONCRETE input coordinates, one
     (gather_rows, scatter_rows) index pair per kernel offset — sparse
     geometry is data-dependent, so it lives outside the traced program,
     exactly like the reference's rulebook construction;
  2. per offset: gather input rows -> one [n_pairs, Cin] x [Cin, Cout]
     matmul (MXU) -> segment-sum into output rows (XLA scatter-add).

Gradients flow through gather/matmul/scatter by construction — no
hand-written backward kernels (the reference needs conv3d_grad CUDA).
Submanifold convs (SubmConv) keep the input coordinate set; regular convs
enumerate reachable output sites. Pooling rides the same rulebook with a
max/mean combine.

Values may be per-point feature rows ([nse, C] with the trailing dim dense),
matching the reference's SparseCooTensor-with-dense-channels layout.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..tensor.creation import _as_t


def _tupleize(v, nd):
    if isinstance(v, (list, tuple)):
        if len(v) != nd:
            raise ValueError(f"expected {nd} entries, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * nd


def _concrete_coords(sp):
    idx = sp.bcoo.indices
    if isinstance(idx, jax.core.Tracer):
        raise NotImplementedError(
            "sparse conv/pool builds its rulebook from concrete coordinates; "
            "indices must not be traced (weights/values may be). Run the "
            "geometry-defining part eagerly, as the reference does.")
    return np.asarray(idx)  # [nse, 1+nd] (batch + spatial)


def _out_spatial(in_sp, k, s, p, d):
    return tuple((i + 2 * pp - dd * (kk - 1) - 1) // ss + 1
                 for i, kk, ss, pp, dd in zip(in_sp, k, s, p, d))


def _ravel(coords, shape):
    """coords [m, 1+nd] -> unique int64 key per site."""
    key = coords[:, 0].astype(np.int64)
    for ax, size in enumerate(shape):
        key = key * int(size) + coords[:, ax + 1].astype(np.int64)
    return key


def _build_rulebook(coords, spatial, ksize, stride, padding, dilation, subm):
    """Return (out_coords [m, 1+nd], rules) where rules is a list of
    (kernel_flat_index, gather_rows, scatter_rows) with non-empty pairs."""
    nd = len(spatial)
    offsets = np.stack(np.meshgrid(
        *[np.arange(k) for k in ksize], indexing="ij"), -1).reshape(-1, nd)
    stride_a = np.asarray(stride)
    pad_a = np.asarray(padding)
    dil_a = np.asarray(dilation)

    if subm:
        out_spatial = tuple(spatial)
    else:
        out_spatial = _out_spatial(spatial, ksize, stride, padding, dilation)
    out_sp_a = np.asarray(out_spatial)

    # one pass per kernel offset: (gather rows, candidate output coords)
    per_offset = []  # (kernel_flat_index, gather_rows, out_coords [m_k, 1+nd])
    for fk, off in enumerate(offsets):
        num = coords[:, 1:] + pad_a - off * dil_a
        ok = (num % stride_a == 0).all(1)
        o = num // stride_a
        ok &= ((o >= 0) & (o < out_sp_a)).all(1)
        if ok.any():
            per_offset.append((fk, np.nonzero(ok)[0],
                               np.concatenate([coords[ok, :1], o[ok]], 1)))

    if subm:
        sorted_key = np.sort(_ravel(coords, out_spatial))
        order = np.argsort(_ravel(coords, out_spatial))
        out_coords = coords
    else:
        if not per_offset:
            return np.zeros((0, 1 + nd), np.int32), out_spatial, []
        allc = np.concatenate([oc for _, _, oc in per_offset], 0)
        uniq, first = np.unique(_ravel(allc, out_spatial), return_index=True)
        out_coords = allc[first]
        sorted_key = uniq
        order = np.arange(len(uniq))

    rules = []
    for fk, gather, ocs in per_offset:
        okey = _ravel(ocs, out_spatial)
        pos = np.searchsorted(sorted_key, okey)
        if subm:
            # submanifold: only outputs that are existing input sites
            valid = (pos < len(sorted_key)) & \
                (sorted_key[np.clip(pos, 0, len(sorted_key) - 1)] == okey)
            if not valid.any():
                continue
            gather = gather[valid]
            scatter = order[pos[valid]]
        else:
            scatter = pos  # every candidate site exists by construction
        rules.append((fk, gather.astype(np.int32), scatter.astype(np.int32)))
    return out_coords.astype(np.int32), out_spatial, rules


def _conv_values(values, weight, rules, m):
    """values [nse, Cin], weight [Kflat, Cin, Cout] -> out values [m, Cout]."""
    out = jnp.zeros((m, weight.shape[-1]), values.dtype)
    for fk, gather, scatter in rules:
        contrib = jnp.take(values, jnp.asarray(gather), axis=0) @ \
            weight[fk].astype(values.dtype)
        out = out.at[jnp.asarray(scatter)].add(contrib)
    return out


def _coo_conv(x, weight, bias, ksize, stride, padding, dilation, subm):
    from . import SparseCooTensor, sparse_coo_tensor

    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse conv expects a SparseCooTensor input")
    nd = len(ksize)
    shape = tuple(int(s) for s in x.bcoo.shape)
    if len(shape) != nd + 2:
        raise ValueError(
            f"expected input rank {nd + 2} [N, *spatial, C], got {shape}")
    spatial = shape[1:-1]
    cin = shape[-1]
    coords = _concrete_coords(x)
    if coords.shape[1] != nd + 1:
        raise ValueError(
            f"expected {nd + 1} sparse dims (batch + spatial) with dense "
            f"channels; got {coords.shape[1]} sparse dims — construct the "
            "input with values of shape [nse, C]")
    out_coords, out_spatial, rules = _build_rulebook(
        coords, spatial, ksize, stride, padding, dilation, subm)

    w = _as_t(weight)
    cout = int(w.shape[-1])
    wk = w.reshape([-1, cin, cout])
    m = out_coords.shape[0]
    args = [x.values(), wk] + ([_as_t(bias)] if bias is not None else [])

    def f(vals, wflat, *b):
        out = _conv_values(vals, wflat, rules, m)
        if b:
            out = out + b[0]
        return out

    out_vals = apply(f, *args, _op_name="sparse_conv")
    out_shape = (shape[0],) + tuple(out_spatial) + (cout,)
    return sparse_coo_tensor(Tensor(jnp.asarray(out_coords.T)), out_vals,
                             list(out_shape))


def _coo_pool(x, ksize, stride, padding, mode):
    from . import SparseCooTensor, sparse_coo_tensor

    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse pool expects a SparseCooTensor input")
    nd = len(ksize)
    shape = tuple(int(s) for s in x.bcoo.shape)
    spatial = shape[1:-1]
    coords = _concrete_coords(x)
    dilation = (1,) * nd
    out_coords, out_spatial, rules = _build_rulebook(
        coords, spatial, ksize, stride, padding, dilation, subm=False)
    m = out_coords.shape[0]
    c = shape[-1]

    def f(vals):
        if mode == "max":
            # segment-max over contributing rows; empty segments impossible
            # (every output site has >= 1 contributor by construction)
            out = jnp.full((m, c), -jnp.inf, vals.dtype)
            for _, gather, scatter in rules:
                out = out.at[jnp.asarray(scatter)].max(
                    jnp.take(vals, jnp.asarray(gather), axis=0))
            return out
        out = jnp.zeros((m, c), vals.dtype)
        cnt = jnp.zeros((m, 1), vals.dtype)
        for _, gather, scatter in rules:
            out = out.at[jnp.asarray(scatter)].add(
                jnp.take(vals, jnp.asarray(gather), axis=0))
            cnt = cnt.at[jnp.asarray(scatter)].add(1.0)
        return out / cnt

    out_vals = apply(f, x.values(), _op_name=f"sparse_{mode}_pool")
    out_shape = (shape[0],) + tuple(out_spatial) + (c,)
    return sparse_coo_tensor(Tensor(jnp.asarray(out_coords.T)), out_vals,
                             list(out_shape))


# ---------------------------------------------------------------- functional

def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", key=None, name=None):
    """weight: [kD, kH, kW, Cin, Cout] (reference sparse conv layout)."""
    if groups != 1:
        raise NotImplementedError("sparse conv groups != 1")
    w = _as_t(weight)
    ksize = tuple(int(s) for s in w.shape[:3])
    return _coo_conv(x, w, bias, ksize, _tupleize(stride, 3),
                     _tupleize(padding, 3), _tupleize(dilation, 3), subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    if groups != 1:
        raise NotImplementedError("sparse conv groups != 1")
    w = _as_t(weight)
    ksize = tuple(int(s) for s in w.shape[:3])
    if _tupleize(stride, 3) != (1, 1, 1):
        raise ValueError("submanifold conv requires stride 1")
    return _coo_conv(x, w, bias, ksize, (1, 1, 1), _tupleize(padding, 3),
                     _tupleize(dilation, 3), subm=True)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", key=None, name=None):
    """weight: [kH, kW, Cin, Cout]."""
    if groups != 1:
        raise NotImplementedError("sparse conv groups != 1")
    w = _as_t(weight)
    ksize = tuple(int(s) for s in w.shape[:2])
    return _coo_conv(x, w, bias, ksize, _tupleize(stride, 2),
                     _tupleize(padding, 2), _tupleize(dilation, 2), subm=False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if groups != 1:
        raise NotImplementedError("sparse conv groups != 1")
    w = _as_t(weight)
    ksize = tuple(int(s) for s in w.shape[:2])
    if _tupleize(stride, 2) != (1, 1):
        raise ValueError("submanifold conv requires stride 1")
    return _coo_conv(x, w, bias, ksize, (1, 1), _tupleize(padding, 2),
                     _tupleize(dilation, 2), subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, data_format="NDHWC",
               name=None):
    k = _tupleize(kernel_size, 3)
    s = _tupleize(stride, 3) if stride is not None else k
    return _coo_pool(x, k, s, _tupleize(padding, 3), "max")


def avg_pool3d(x, kernel_size, stride=None, padding=0, data_format="NDHWC",
               name=None):
    k = _tupleize(kernel_size, 3)
    s = _tupleize(stride, 3) if stride is not None else k
    return _coo_pool(x, k, s, _tupleize(padding, 3), "avg")
