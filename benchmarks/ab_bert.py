#!/usr/bin/env python
"""BERT b32xs128 shape-physics A/B (VERDICT r4 item 3): test the claimed
"small-GEMM shape physics at h=768" BEFORE believing it.

Variants (each in a fresh process so PADDLE_TPU_FUSE_QKV binds at model
build):
  base      — b32xs128, three separate [768,768] QKV GEMMs (family row)
  fuseqkv   — b32xs128, QKV as ONE [768,2304] GEMM (in-trace weight
              concat; checkpoint layout unchanged)
  pack      — b16xs256, same tokens/step as b32xs128 (the sequence-
              packing SHAPE experiment: GEMM M stays 4096, attention
              runs at s256 — measures geometry, not packing semantics)
  fuse+pack — both

All variants run scan8 (one dispatch per 8 steps — the tunnel-noise-free
driver) and the ABBA order decorrelates slow tunnel drift. Prints one
JSON line per run + a summary; writes AB_BERT.json.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VARIANTS = {
    "base": ({}, dict(B=32, scan_k=8, S=128)),
    "fuseqkv": ({"PADDLE_TPU_FUSE_QKV": "1"}, dict(B=32, scan_k=8, S=128)),
    "pack": ({}, dict(B=16, scan_k=8, S=256)),
    "fuse+pack": ({"PADDLE_TPU_FUSE_QKV": "1"},
                  dict(B=16, scan_k=8, S=256)),
}

CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {repo!r} + "/benchmarks")
from bench_models import bench_bert
r = bench_bert(**{kwargs})
print("ABRESULT " + json.dumps(r))
"""


def run_one(name):
    env_extra, kwargs = VARIANTS[name]
    env = dict(os.environ, **env_extra)
    code = CHILD.format(repo=REPO, kwargs=repr(kwargs))
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=1200, env=env)
    if r.returncode != 0:
        raise RuntimeError(f"{name}: {r.stdout[-800:]} {r.stderr[-800:]}")
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("ABRESULT ")][-1]
    res = json.loads(line[len("ABRESULT "):])
    toks = res["value"] * (256 if "pack" in name else 128)
    out = {"variant": name, "seqs_per_s": res["value"],
           "tokens_per_s": round(toks, 0),
           "metric": res["metric"],
           "device_pct_ceiling": res.get("pct_of_ceiling")}
    print(json.dumps(out), flush=True)
    return out


def main():
    order = ["base", "fuseqkv", "pack", "fuse+pack",
             "fuse+pack", "pack", "fuseqkv", "base"]   # ABBA-style
    runs = [run_one(n) for n in order]
    by = {}
    for r in runs:
        by.setdefault(r["variant"], []).append(r["tokens_per_s"])
    summary = {v: {"tokens_per_s_best": max(ts),
                   "tokens_per_s_all": ts} for v, ts in by.items()}
    base = summary["base"]["tokens_per_s_best"]
    for v, s in summary.items():
        s["vs_base"] = round(s["tokens_per_s_best"] / base, 4)
    print(json.dumps(summary, indent=1))
    with open(os.path.join(REPO, "AB_BERT.json"), "w") as f:
        json.dump({"runs": runs, "summary": summary}, f, indent=1)


if __name__ == "__main__":
    main()
