#!/usr/bin/env python
"""hapi Model.fit: eager-per-batch vs prepare(jit=True) (VERDICT r4
item 9) — measure the gap on one family so the default is a recorded
decision, not a guess. Runs BERT-base MLM-sized batches through
Model.train_batch both ways on the current backend."""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(jit):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.models import BertConfig, BertForMaskedLM

    paddle.seed(0)
    on_tpu = __import__("jax").default_backend() in ("tpu", "axon")
    cfg = (BertConfig(vocab_size=30522, hidden_size=768,
                      num_hidden_layers=12, num_attention_heads=12,
                      intermediate_size=3072,
                      max_position_embeddings=512) if on_tpu else
           BertConfig(vocab_size=1024, hidden_size=128,
                      num_hidden_layers=2, num_attention_heads=2,
                      intermediate_size=256, max_position_embeddings=128))
    B, S, steps, windows = (32, 128, 8, 3) if on_tpu else (4, 32, 3, 1)

    class MLMNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bert = BertForMaskedLM(cfg)

        def forward(self, ids):
            out = self.bert(ids)
            return out[0] if isinstance(out, tuple) else out

    class MLMLoss(nn.Layer):
        def forward(self, logits, labels):
            return nn.functional.cross_entropy(
                logits.reshape([-1, cfg.vocab_size]),
                labels.reshape([-1]))

    net = MLMNet()
    if on_tpu:
        net.to(dtype="bfloat16")
    model = Model(net)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=net.parameters(),
                                 multi_precision=True)
    model.prepare(optimizer=opt, loss=MLMLoss(), jit=jit)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32))

    model.train_batch([ids], [ids])      # compile/warm
    model.train_batch([ids], [ids])
    best = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            (lv,) = model.train_batch([ids], [ids])
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return {"jit": jit, "seqs_per_s": round(B * steps / best, 1),
            "last_loss": round(lv, 4)}


def main():
    a = run(False)
    b = run(True)
    out = {"eager": a, "jit": b,
           "speedup": round(b["seqs_per_s"] / a["seqs_per_s"], 2)}
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "AB_HAPI_FIT.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
