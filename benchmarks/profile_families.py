#!/usr/bin/env python
"""Per-op-class device-time attribution for the bench families (VERDICT r3
item 1): capture a jax.profiler device trace of the exact compiled train
step each family benches, then aggregate HLO self-time by op category via
xprof's hlo_stats converter.

    python benchmarks/profile_families.py resnet50|bert|unet [--trace-dir D]

Prints a JSON report: total device time/step, per-category time share,
top-15 individual ops with source attribution, and the compute/HBM-bound
split. The committed reports live in benchmarks/profiles/.
"""

import collections
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time


def _capture(family, trace_dir):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import jax

    paddle.seed(0)
    if family == "resnet50":
        from paddle_tpu.vision.models import resnet50

        model = resnet50(num_classes=1000)
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=model.parameters())

        def loss_fn(net, x, y):
            return nn.functional.cross_entropy(
                paddle.cast(net(x), "float32"), y)

        rng = np.random.RandomState(0)
        batch = (paddle.cast(paddle.to_tensor(
            rng.randn(64, 3, 224, 224).astype(np.float32)), "bfloat16"),
            paddle.to_tensor(rng.randint(0, 1000, (64,)).astype(np.int64)))
    elif family == "bert":
        from paddle_tpu.models import BertConfig, BertForMaskedLM

        cfg = BertConfig(vocab_size=30522, hidden_size=768,
                         num_hidden_layers=12, num_attention_heads=12,
                         intermediate_size=3072,
                         max_position_embeddings=512)
        model = BertForMaskedLM(cfg)
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)

        def loss_fn(net, ids, labels):
            out = net(ids, labels=labels)
            return out[0] if isinstance(out, tuple) else out

        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, 30522, (32, 128)).astype(np.int32))
        batch = (ids, ids)
    elif family == "unet":
        from paddle_tpu.models import UNetConfig, UNet2DConditionModel

        cfg = UNetConfig()
        model = UNet2DConditionModel(cfg)
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)

        def loss_fn(net, x, t, ctx, target):
            return nn.functional.mse_loss(net(x, t, ctx), target)

        rng = np.random.RandomState(0)
        lat = paddle.cast(paddle.to_tensor(
            rng.randn(4, cfg.in_channels, 32, 32).astype(np.float32)),
            "bfloat16")
        batch = (lat,
                 paddle.to_tensor(rng.randint(0, 1000, (4,)).astype(np.int32)),
                 paddle.cast(paddle.to_tensor(
                     rng.randn(4, 77, cfg.cross_attention_dim)
                     .astype(np.float32)), "bfloat16"),
                 lat)
    else:
        raise SystemExit(f"unknown family {family}")

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    float(step(*batch))
    float(step(*batch))
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)
    n_steps = 5
    jax.profiler.start_trace(trace_dir)
    for _ in range(n_steps):
        out = step(*batch)
    float(out)
    jax.profiler.stop_trace()
    return n_steps


def _source_of(row):
    info = row.get("source_info") or ""
    if "title='" in info:
        first = info.split("title='", 1)[1].split("\n", 1)[0]
        return first.replace("/root/repo/", "")
    return ""


def analyze(trace_dir, n_steps):
    from xprof.convert import raw_to_tool_data as r

    (path,) = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    data, _ = r.xspace_to_tool_data([path], "hlo_stats", {})
    j = json.loads(data)
    cols = [c["id"] for c in j["cols"]]
    rows = [dict(zip(cols, [c.get("v") for c in row["c"]]))
            for row in j["rows"]]

    total_us = sum(r_["total_self_time"] for r_ in rows)
    by_cat = collections.defaultdict(lambda: [0.0, 0.0, 0.0])  # us, hbm, n
    for r_ in rows:
        cat = r_["category"]
        by_cat[cat][0] += r_["total_self_time"]
        if r_.get("bound_by") == "HBM":
            by_cat[cat][1] += r_["total_self_time"]
        by_cat[cat][2] += r_.get("occurrences", 0)

    cats = [{"category": c, "us_per_step": round(v[0] / n_steps, 1),
             "pct": round(100 * v[0] / total_us, 1),
             "hbm_bound_pct": round(100 * v[1] / max(v[0], 1e-9), 0),
             "ops_per_step": int(v[2] / n_steps)}
            for c, v in sorted(by_cat.items(), key=lambda kv: -kv[1][0])]
    top = [{"op": r_["hlo_op_name"], "category": r_["category"],
            "us_per_step": round(r_["total_self_time"] / n_steps, 1),
            "pct": round(r_["total_self_time_percent"], 2),
            "bound_by": r_.get("bound_by"),
            "flop_rate_gflops": round(r_.get("model_flop_rate") or 0, 1),
            "hbm_gbps": round(r_.get("hbm_bw") or 0, 1),
            "source": _source_of(r_)}
           for r_ in rows[:15]]
    hbm_us = sum(r_["total_self_time"] for r_ in rows
                 if r_.get("bound_by") == "HBM")
    return {"device_us_per_step": round(total_us / n_steps, 1),
            "hbm_bound_pct_of_time": round(100 * hbm_us / total_us, 1),
            "by_category": cats[:14], "top_ops": top}


def main():
    family = sys.argv[1]
    trace_dir = f"/tmp/prof_{family}"
    if "--trace-dir" in sys.argv:
        trace_dir = sys.argv[sys.argv.index("--trace-dir") + 1]
    if "--analyze-only" not in sys.argv:
        n = _capture(family, trace_dir)
    else:
        n = 5
    rep = analyze(trace_dir, n)
    rep["family"] = family
    print(json.dumps(rep, indent=1))


if __name__ == "__main__":
    main()
