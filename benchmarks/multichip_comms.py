#!/usr/bin/env python
"""Explicit-collective multichip configs for the comms ledger.

One module, two consumers: ``bench_models.py multichip_comms`` (which
runs this file in a subprocess on 8 virtual CPU devices and writes the
rows into MULTICHIP_BENCH.json) and ``tests/test_comms_observability.py``
(which asserts the jaxpr walker's counts equal the hand-derived
``expected`` census of every config).

Each config is a small shard_map program written with EXPLICIT lax
collectives — the shapes the MULTICHIP dryruns exercise (dp grad sync,
dp×mp hybrid, pipeline ring, ring attention, ZeRO-3 gather/scatter,
MoE expert-parallel) distilled to their communication skeletons.
Honesty note: the dryruns' pjit/GSPMD variants (auto-sharded dp×mp,
``group_sharded`` ZeRO) get their collectives inserted during XLA SPMD
partitioning, where no jaxpr walker can see them — so the bench gates
the explicit shard_map skeletons, whose censuses are exact by
construction.  The dp4xmp2 config writes BOTH psums by hand (the mp
activation reduce and the dp grad sync) rather than relying on
``jax.grad``'s psum transposition, so the expected counts stay stable
across jax autodiff versions.

Run directly (prints one JSON row per config, then a sentinel):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python benchmarks/multichip_comms.py
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SENTINEL = "MULTICHIP_COMMS_OK"


def _mesh(axis_sizes):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    n = 1
    for v in axis_sizes.values():
        n *= v
    devs = np.array(jax.devices()[:n]).reshape(tuple(axis_sizes.values()))
    return Mesh(devs, tuple(axis_sizes))


# ---------------------------------------------------------------- configs
def build_dp8():
    """Pure data parallel over 8 ranks: one psum grad sync per step."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map

    mesh = _mesh({"dp": 8})

    def step(x):
        g = x * 2.0 + 1.0            # stand-in local gradient
        return lax.psum(g, "dp")

    fn = shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P(),
                   **NO_CHECK)
    x = jnp.ones((8, 64), jnp.float32)
    return fn, (x,), {("psum", "dp"): 1}


def build_dp4xmp2():
    """Hybrid dp4×mp2: the mp activation reduce and the dp grad sync,
    both written explicitly."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map

    mesh = _mesh({"dp": 4, "mp": 2})

    def step(x, w):
        # x [b_loc, k_loc], w [k_loc, out]: row-parallel matmul — each
        # mp rank holds a K-slice, partial products sum across 'mp'
        y = lax.psum(x @ w, "mp")
        gw = x.T @ y                 # stand-in local weight gradient
        return lax.psum(gw, "dp")    # data-parallel grad sync

    fn = shard_map(step, mesh=mesh, in_specs=(P("dp", "mp"), P("mp", None)),
                   out_specs=P(), **NO_CHECK)
    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 8), jnp.float32) * 0.1
    return fn, (x, w), {("psum", "mp"): 1, ("psum", "dp"): 1}


def build_pp2_1f1b():
    """Pipeline ring at S=2, M=4 microbatches on the 1F1B clock:
    T = M + 2(D-1) = 6 ticks, one boundary ppermute each, one final
    loss psum across 'pp'."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map

    S, M = 2, 4
    ticks = M + 2 * (S - 1)          # 1f1b tick count, D = S·V, V=1
    mesh = _mesh({"pp": 2})
    perm = [(i, (i + 1) % S) for i in range(S)]

    def step(h):
        def tick(carry, _):
            carry = lax.ppermute(carry, "pp", perm)
            return carry * 1.01, ()

        h, _ = lax.scan(tick, h, jnp.arange(ticks))
        return lax.psum((h * h).sum(), "pp")

    fn = shard_map(step, mesh=mesh, in_specs=P("pp"), out_specs=P(),
                   **NO_CHECK)
    h = jnp.ones((2, 16), jnp.float32)
    return fn, (h,), {("ppermute", "pp"): ticks, ("psum", "pp"): 1}


def build_ring_sep4():
    """The real ring attention forward over sep=4: the k and v blocks
    each rotate once per ring step, scan length = axis size, so the
    census is exactly 2·sep ppermutes."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.ring_attention import (
        ring_flash_attention_arrays)
    from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map

    sep = 4
    mesh = _mesh({"sep": sep})

    def step(q, k, v):
        return ring_flash_attention_arrays(q, k, v, causal=True,
                                           axis_name="sep")

    spec = P(None, "sep", None, None)      # [B, S, H, D] sharded on S
    fn = shard_map(step, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, **NO_CHECK)
    q = jnp.ones((1, 512, 4, 64), jnp.float32) * 0.02
    return fn, (q, q, q), {("ppermute", "sep"): 2 * sep}


def build_zero3_sharding8():
    """ZeRO-3 skeleton over sharding=8: gather each param shard before
    use, reduce-scatter each grad back — one all_gather + psum_scatter
    pair per parameter."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map

    mesh = _mesh({"sharding": 8})

    def step(x, w1, w2):
        w1f = lax.all_gather(w1, "sharding", axis=0, tiled=True)
        w2f = lax.all_gather(w2, "sharding", axis=0, tiled=True)
        h = jax.nn.relu(x @ w1f)
        y = h @ w2f
        g1f = x.T @ h                # stand-in full grads
        g2f = h.T @ y
        g1 = lax.psum_scatter(g1f, "sharding", scatter_dimension=0,
                              tiled=True)
        g2 = lax.psum_scatter(g2f, "sharding", scatter_dimension=0,
                              tiled=True)
        return g1, g2

    fn = shard_map(
        step, mesh=mesh,
        in_specs=(P("sharding", None), P("sharding", None),
                  P("sharding", None)),
        out_specs=(P("sharding", None), P("sharding", None)), **NO_CHECK)
    x = jnp.ones((8, 64), jnp.float32) * 0.1
    w1 = jnp.ones((64, 32), jnp.float32) * 0.05
    w2 = jnp.ones((32, 16), jnp.float32) * 0.05
    return fn, (x, w1, w2), {("all_gather", "sharding"): 2,
                             ("psum_scatter", "sharding"): 2}


def build_moe_ep4():
    """The real MoELayer expert-parallel path on dp=4 (8 experts, 2 per
    rank): one all_to_all to deal capacity buffers to expert owners, one
    to deal results back."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    mesh = _mesh({"dp": 4})
    layer = MoELayer(d_model=16, d_hidden=32, num_experts=8,
                     axis_name="dp")
    weights = tuple(p._data for p in (layer.gate_weight, layer.w1,
                                      layer.b1, layer.w2, layer.b2))

    def step(x, gw, w1, b1, w2, b2):
        y, aux, tok = layer._forward_arrays(x, gw, w1, b1, w2, b2, "dp")
        return y, aux, tok

    fn = shard_map(
        step, mesh=mesh,
        in_specs=(P("dp", None),) + (P(None),) * 5,
        out_specs=(P("dp", None), P(), P()), **NO_CHECK)
    x = jnp.ones((64, 16), jnp.float32) * 0.1
    return fn, (x,) + weights, {("all_to_all", "dp"): 2}


def build_sharded_decode_tp2():
    """The REAL sharded-serving decode program: a tp=2 MeshEngine's
    horizon-scanned fused decode (``_decode_fn``, horizon=4) over the
    mesh-sharded paged pool.  Census is the hand-derived per-layer
    count: per scanned step, 1 psum head-combine + 3 all_gathers per
    layer (o_proj, SwiGLU intermediate, down_proj) + 1 all_gather for
    the lm_head logits — L=2, h=4 gives psum@tp=8, all_gather@tp=28.
    Unlike the skeletons above this walks a full engine program
    (shard_map under lax.scan under the sampling/masking machinery), so
    it also pins the walker's scan×shard_map multiplication."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import EngineConfig, MeshEngine

    cfg = GPTConfig(vocab_size=128, hidden_size=64,
                    intermediate_size=128, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=64)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    eng = MeshEngine(m, EngineConfig(num_slots=2, max_seq_len=32,
                                     max_horizon=4),
                     tp=2, register_profiler=False)
    horizon = 4
    fn, args = eng.decode_census_program(horizon=horizon)
    return fn, args, eng.expected_decode_census(horizon)


CONFIGS = {
    "dp8": build_dp8,
    "dp4xmp2": build_dp4xmp2,
    "pp2_1f1b": build_pp2_1f1b,
    "ring_sep4": build_ring_sep4,
    "zero3_sharding8": build_zero3_sharding8,
    "moe_ep4": build_moe_ep4,
    "sharded_decode_tp2": build_sharded_decode_tp2,
}


# ------------------------------------------------------------------ rows
def measure_config(name, steps=4, windows=2):
    """Build one config, walk its jaxpr, time its dispatches; returns the
    MULTICHIP_BENCH row (sans provenance fields, which the writer in
    bench_models.py stamps)."""
    import jax

    from paddle_tpu.observability import comms

    fn, args, expected = CONFIGS[name]()
    report = comms.analyze_fn(fn, *args)
    got = report.counts()
    if got != expected:
        raise AssertionError(
            f"{name}: walker census {got} != hand-derived {expected}")

    jitted = jax.jit(fn)
    jax.block_until_ready(jitted(*args))     # compile + warm
    best = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            out = jitted(*args)
        jax.block_until_ready(out)
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    step_s = best / steps

    backend = jax.default_backend()
    comms_s = comms.modeled_comms_seconds(report, backend)
    comms.publish_dispatch("multichip", name, report, step_s, backend)
    by_op = report.calls_by_op()
    row = {
        "metric": f"multichip comms {name} step (cpu8)",
        "value": round(step_s * 1e3, 3),
        "unit": "ms",
        "collective_calls_total": report.total_calls,
        "modeled_wire_bytes_per_step": round(report.total_wire_bytes, 1),
        "comms_roofline_pct": round(100.0 * comms_s / step_s, 2)
        if step_s > 0 else None,
        "counts_by_op_axis": {f"{op}@{ax}": c
                              for (op, ax), c in sorted(got.items())},
    }
    for op in comms.COLLECTIVE_OPS:
        row[f"{op}_calls"] = by_op.get(op, 0)
    return row


def main(argv=None):
    names = [a for a in (argv or sys.argv[1:]) if not a.startswith("-")]
    for name in names or list(CONFIGS):
        try:
            print(json.dumps(measure_config(name)), flush=True)
        except Exception as e:       # report, keep going
            print(json.dumps({
                "metric": f"multichip comms {name} step (cpu8)",
                "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)
    print(SENTINEL, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
