#!/usr/bin/env python
"""DataLoader worker-mode throughput: sync vs threads vs spawn processes on
the two workload archetypes (VERDICT r2 item 6 — measure, don't assume).

GIL-releasing work (NumPy image-ish decode) favors threads: no pickle hop,
no process startup. GIL-holding work (pure-Python tokenize-ish) is where
process workers earn their keep. Prints one JSON line per (workload, mode).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class NumpyHeavyDS:
    """GIL-releasing: fft+matmul over a 256x256 block per sample."""

    def __len__(self):
        return 256

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        a = rng.randn(256, 256).astype(np.float32)
        return np.abs(np.fft.rfft2(a @ a.T)).astype(np.float32)


class PythonHeavyDS:
    """GIL-holding: pure-Python token munging per sample."""

    def __len__(self):
        return 256

    def __getitem__(self, i):
        text = ("tok%d " % i) * 4000
        toks = [hash(w) % 32000 for w in text.split()]
        out = []
        for t in toks:
            out.append((t * 31 + 7) % 32000)
        return np.asarray(out[:1024], np.int32)


def run(ds, mode, workers=4):
    from paddle_tpu.io import DataLoader

    kw = {}
    if mode == "threads":
        kw = dict(num_workers=workers)
    elif mode == "procs":
        kw = dict(num_workers=workers, use_process_workers=True, timeout=300)
    dl = DataLoader(ds, batch_size=16, **kw)
    list(dl)  # warm (spawn startup, caches)
    t0 = time.time()
    n = sum(b.shape[0] if hasattr(b, "shape") else len(b) for b in dl)
    dt = time.time() - t0
    return n / dt


def main():
    for name, ds in (("numpy_heavy", NumpyHeavyDS()),
                     ("python_heavy", PythonHeavyDS())):
        for mode in ("sync", "threads", "procs"):
            sps = run(ds, mode)
            print(json.dumps({"workload": name, "mode": mode,
                              "samples_per_sec": round(sps, 1)}))


if __name__ == "__main__":
    main()
