#!/usr/bin/env python
"""Autoregressive generation benchmark (VERDICT r4 item 7): prefill s512
+ 128 greedy decode steps through fused_multi_transformer with inline
rotary and a fixed-capacity KV cache — the serving path the reference
ships as AnalysisPredictor + fused CUDA decode ops (SURVEY §2.1 N19).

Decode drivers measured:
  * per-step: one jitted step per token, caches DONATED (in-place HBM
    cache update) — the latency-interactive shape;
  * scan128: all 128 steps as ONE lax.scan program (one dispatch) — the
    TPU-native offline/serving shape; on a tunneled chip this is also
    the dispatch-noise-free number;
  * engine horizon rows: serving.Engine at fixed horizon 1/4/8/16 — the
    continuous-batching engine's horizon-scanned decode (one dispatch +
    one host sync per H steps), reporting how much of the per-step
    host overhead the horizon amortizes and the roofline % recovered;
  * paged-ablation rows: ragged paged attention vs full-width table
    reads (tok/s, KV bytes/step, decode tokens per GB of KV traffic) —
    see _bench_paged_ablation for the b8 scan-regression diagnosis
    these rows ablate;
  * quant-ablation rows: fp vs int8 weight-only vs int8 weights + int8
    paged KV (tok/s, KV bytes/step, weight bytes) plus a fixed-byte-
    budget capacity row — see _bench_quant_ablation.

Roofline math uses a per-backend bandwidth table (TPU datasheet
numbers) with a one-shot memcpy probe for unlisted backends, so CPU
rows carry an honest ``roofline_bw_gbs`` instead of omitting the
column (see _backend_bandwidth_gbs).

A numerics gate runs first ON THE BENCH DEVICE: fused cached decode must
match the fused prefill of the concatenated sequence (self-consistency)
AND the unfused dense composition (small config), so a kernel regression
fails loudly before any timing. Prints one JSON line per metric; writes
DECODE_BENCH.json at the repo root when run there.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# The roofline bandwidth table + memcpy probe moved to
# paddle_tpu.observability.memory (observability phase 3) so the live
# engine gauge and every bench section judge against the SAME number;
# the old name stays as the bench-local alias.
from paddle_tpu.observability.memory import (        # noqa: E402
    backend_bandwidth_gbs as _backend_bandwidth_gbs)


def _build_params(rng, L, dim, n_head, ffn, dtype):
    import jax.numpy as jnp

    hd = dim // n_head

    def mk(*sh):
        return jnp.asarray((rng.randn(*sh) * 0.02).astype(np.float32),
                           dtype)

    return dict(
        ln_scales=[mk(dim) + 1 for _ in range(L)],
        ln_biases=[mk(dim) for _ in range(L)],
        qkv_weights=[mk(3, n_head, hd, dim) for _ in range(L)],
        qkv_biases=[mk(3 * n_head * hd) for _ in range(L)],
        linear_weights=[mk(dim, dim) for _ in range(L)],
        linear_biases=[mk(dim) for _ in range(L)],
        ffn_ln_scales=[mk(dim) + 1 for _ in range(L)],
        ffn_ln_biases=[mk(dim) for _ in range(L)],
        ffn1_weights=[mk(dim, ffn) for _ in range(L)],
        ffn1_biases=[mk(ffn) for _ in range(L)],
        ffn2_weights=[mk(ffn, dim) for _ in range(L)],
        ffn2_biases=[mk(dim) for _ in range(L)],
    )


def _rotary_tables(b, max_seq, hd, dtype):
    """Packed [2, b, 1, max_seq, hd] cos/sin, full head_dim (the fused
    kernel's inline-rope contract)."""
    import jax.numpy as jnp

    pos = np.arange(max_seq, dtype=np.float32)
    inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2, np.float32) / hd))
    ang = np.einsum("s,d->sd", pos, inv)                  # [s, hd/2]
    ang = np.repeat(ang, 2, axis=-1)                      # full head_dim
    cos = np.broadcast_to(np.cos(ang), (b, 1, max_seq, hd))
    sin = np.broadcast_to(np.sin(ang), (b, 1, max_seq, hd))
    return jnp.asarray(np.stack([cos, sin]), dtype)


def _make_fns(L, dim, n_head, ffn, vocab, max_seq, dtype):
    """(prefill, step, scan_decode) pure-array jitted functions."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import tape as _tape
    from paddle_tpu.core.tensor import Tensor
    import paddle_tpu.incubate.nn.functional as IF

    hd = dim // n_head

    def run_layers(P, x_arr, caches, time_step):
        PT = {k: [Tensor(a) for a in v] for k, v in P["layers"].items()}
        with _tape.no_grad():
            out, new_caches = IF.fused_multi_transformer(
                Tensor(x_arr), cache_kvs=[Tensor(c) for c in caches],
                rotary_embs=Tensor(P["rotary"]), rotary_emb_dims=1,
                use_neox_rotary_style=True,
                time_step=(None if time_step is None
                           else Tensor(time_step)),
                **PT)
        return out._data, [c._data for c in new_caches]

    def logits_of(P, h_last):
        # bf16 weight reads, f32 accumulation: upcasting the [dim, vocab]
        # head to f32 would double its HBM traffic — the biggest single
        # read of a decode step
        return jnp.matmul(h_last, P["lm_head"],
                          preferred_element_type=jnp.float32)

    def prefill(P, ids, caches):
        x = P["embed"][ids]                               # [b, s, dim]
        h, caches = run_layers(P, x, caches, None)
        return (jnp.argmax(logits_of(P, h[:, -1]), -1).astype(jnp.int32),
                caches)

    def step(P, tok, t, caches):
        x = P["embed"][tok][:, None, :]                   # [b, 1, dim]
        h, caches = run_layers(P, x, caches, t)
        return (jnp.argmax(logits_of(P, h[:, 0]), -1).astype(jnp.int32),
                caches)

    def scan_decode(P, tok0, t0, caches, n_steps):
        def body(carry, _):
            tok, t, cs = carry
            nxt, cs = step(P, tok, t, cs)
            return (nxt, t + 1, tuple(cs)), nxt

        (_, _, caches), toks = jax.lax.scan(
            body, (tok0, t0, tuple(caches)), None, length=n_steps)
        return toks, caches

    jit_prefill = jax.jit(prefill, donate_argnums=(2,))
    jit_step = jax.jit(step, donate_argnums=(3,))
    jit_scan = jax.jit(scan_decode, donate_argnums=(3,),
                       static_argnums=(4,))
    return jit_prefill, jit_step, jit_scan


def _numerics_gate(dtype):
    """Fused cached decode vs fused prefill (self-consistency) and vs the
    unfused dense composition, on the CURRENT device."""
    import jax.numpy as jnp

    from paddle_tpu.core import tape as _tape
    from paddle_tpu.core.tensor import Tensor
    import paddle_tpu.incubate.nn.functional as IF
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    L, dim, n_head, ffn, seq, max_seq = 2, 128, 2, 256, 6, 16
    hd = dim // n_head
    P = _build_params(rng, L, dim, n_head, ffn, jnp.float32)
    PT = {k: [Tensor(a) for a in v] for k, v in P.items()}
    x = Tensor(jnp.asarray(rng.randn(1, seq, dim).astype(np.float32) * .3))
    rot = Tensor(_rotary_tables(1, max_seq, hd, jnp.float32))
    with _tape.no_grad():
        full = IF.fused_multi_transformer(
            x, rotary_embs=rot, rotary_emb_dims=1,
            use_neox_rotary_style=True, **PT)
        caches = [Tensor(jnp.zeros((2, 1, n_head, max_seq, hd)))
                  for _ in range(L)]
        for t in range(seq):
            out, caches = IF.fused_multi_transformer(
                x[:, t:t + 1], cache_kvs=caches,
                rotary_embs=rot, rotary_emb_dims=1,
                use_neox_rotary_style=True,
                time_step=Tensor(jnp.asarray(t, jnp.int32)), **PT)
    err = np.abs(np.asarray(out._data)[:, 0]
                 - np.asarray(full._data)[:, -1]).max()
    assert err < 2e-3, f"decode-vs-prefill mismatch: {err}"

    # prefill (no rotary) vs unfused dense composition
    with _tape.no_grad():
        nr = IF.fused_multi_transformer(x, **PT)
        h = x
        for i in range(L):
            ln = F.layer_norm(h, [dim], PT["ln_scales"][i],
                              PT["ln_biases"][i])
            qw = np.asarray(P["qkv_weights"][i])
            qkv = np.einsum("bsd,thed->bsthe", np.asarray(ln._data), qw) \
                + np.asarray(P["qkv_biases"][i]).reshape(1, 1, 3, n_head,
                                                         hd)
            q, k, v = (Tensor(jnp.asarray(qkv[:, :, j]))
                       for j in range(3))
            att = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                                 training=False)
            att = att.reshape([1, seq, dim])
            o = F.linear(att, PT["linear_weights"][i],
                         PT["linear_biases"][i])
            h = h + o
            ln2 = F.layer_norm(h, [dim], PT["ffn_ln_scales"][i],
                               PT["ffn_ln_biases"][i])
            f1 = F.gelu(F.linear(ln2, PT["ffn1_weights"][i],
                                 PT["ffn1_biases"][i]))
            h = h + F.linear(f1, PT["ffn2_weights"][i],
                             PT["ffn2_biases"][i])
    err2 = np.abs(np.asarray(nr._data) - np.asarray(h._data)).max()
    assert err2 < 2e-3, f"fused-vs-dense mismatch: {err2}"


def _bench_engine_horizons(backend, on_tpu, rng):
    """serving.Engine single-stream decode at fixed horizons 1/4/8/16:
    the engine-side answer to the per-step-vs-scan128 gap above.  Each
    row times a b1 request decoding `new_tokens` through num_slots=1,
    forcing one compiled horizon bucket, and splits wall per-step time
    into device time (one directly-timed horizon dispatch via
    Engine.measure_decode_seconds) and host overhead (admit + harvest +
    dispatch glue) — the quantity horizon scanning amortizes."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, prompt_len, new_tokens = 768, 512, 128
        dtype = jnp.bfloat16
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, prompt_len, new_tokens = 64, 16, 32
        dtype = jnp.float32

    itemsize = jnp.dtype(dtype).itemsize
    dim, ffn, vocab = (cfg.hidden_size, cfg.intermediate_size,
                       cfg.vocab_size)
    layer_w = (4 * dim * dim + 3 * dim * ffn) * cfg.num_hidden_layers
    weight_bytes = (layer_w + dim * vocab) * itemsize
    bw_gbs = _backend_bandwidth_gbs(backend)
    roofline_ms = weight_bytes / (bw_gbs * 1e9) * 1e3

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = rng.randint(0, cfg.vocab_size, prompt_len).tolist()
    sp = SamplingParams(max_new_tokens=new_tokens)
    rows = []
    for horizon in (1, 4, 8, 16):
        eng = Engine(model, EngineConfig(num_slots=1, max_seq_len=max_seq,
                                         max_horizon=16,
                                         cache_dtype=dtype),
                     register_profiler=False)
        # warm both compiles (the prefill bucket + this horizon bucket)
        eng.submit(prompt, sp)
        while eng.scheduler.has_work:
            eng.step(horizon=horizon)
        # timed stream: prefill outside the decode window (matching the
        # per-step/scan rows above), then fixed-horizon decode
        eng.submit(prompt, sp)
        eng.admit()
        kv0 = eng.counters()["kv_bytes_read"]
        t0 = time.time()
        while eng.scheduler.has_work:
            eng.step(horizon=horizon)
        dt = time.time() - t0
        per_step_ms = dt * 1000.0 / new_tokens
        device_s = eng.measure_decode_seconds(horizon)
        host_ms = max(0.0, per_step_ms - device_s * 1000.0 / horizon)
        c = eng.stats()
        kv_bytes = c["kv_pool"]["kv_bytes_read"] - kv0
        eng.close()
        row = {
            "metric": f"engine decode tokens/s b1 horizon{horizon} "
                      f"(prefill {prompt_len} + {new_tokens} new, "
                      f"{backend})",
            "value": round(new_tokens / dt, 1),
            "unit": "tokens/s",
            "per_step_ms": round(per_step_ms, 3),
            "host_overhead_ms": round(host_ms, 3),
            "decode_horizons": c["decode_horizons"],
            "host_syncs": c["decode_host_syncs"],
            # ragged paged attention: bytes of KV pool the decode scans
            # actually gathered this window (table-width buckets x block
            # bytes), and decode throughput per GB of KV traffic
            "kv_bytes_read_per_step": int(kv_bytes // new_tokens),
            "tokens_per_gb_kv_read": round(new_tokens
                                           / (kv_bytes / 1e9), 1),
            "roofline_bw_gbs": bw_gbs,
            "weight_roofline_ms": round(roofline_ms, 3),
            "roofline_pct": round(100.0 * roofline_ms / per_step_ms, 1),
        }
        rows.append(row)
    return rows


def _bench_engine(backend, on_tpu, rng):
    """Continuous-batching throughput through serving.Engine: b8 slots,
    STAGGERED arrivals (requests join at decode-step boundaries while
    earlier ones are mid-stream) — the online-serving shape the per-step
    and scan drivers above cannot express. One fused decode step serves
    every step/request mix, so the row also reports the compile counters
    proving zero retracing across the heterogeneous run."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, prompt_len, new_tokens, n_req = 768, 512, 128, 16
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, prompt_len, new_tokens, n_req = 64, 32, 8, 16

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = Engine(model, EngineConfig(num_slots=8, max_seq_len=max_seq),
                 register_profiler=False)
    prompts = [rng.randint(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_req)]
    sp = SamplingParams(max_new_tokens=new_tokens)

    # warm the compile caches (one prefill bucket + the decode step)
    eng.generate(prompts[0], sp)

    kv0 = eng.counters()["kv_bytes_read"]
    t0 = time.time()
    it = iter(prompts)
    for p in (next(it) for _ in range(8)):        # fill the slots
        eng.submit(p, sp)
    pending = list(it)
    while eng.scheduler.has_work:
        finished = eng.step()
        if pending and finished:                  # staggered arrivals:
            eng.submit(pending.pop(0), sp)        # join mid-stream
    dt = time.time() - t0
    c = eng.stats()
    kv_bytes = c["kv_pool"]["kv_bytes_read"] - kv0
    toks = c["tokens_generated"] - new_tokens
    eng.close()
    return {
        "metric": f"engine continuous-batching tokens/s b8 staggered "
                  f"(prefill {prompt_len} + {new_tokens} new x {n_req} "
                  f"reqs, {backend})",
        "value": round(toks / dt, 1),
        "unit": "tokens/s",
        "ttft_avg_s": round(c["ttft_avg_s"], 4),
        "slot_utilization": round(c["slot_utilization"], 3),
        "decode_compiles": c["decode_compiles"],
        "prefill_compiles": c["prefill_compiles"],
        "decode_horizons": c["decode_horizons"],
        "horizon_buckets": c["horizon_buckets"],
        "wasted_lane_fraction": round(c["wasted_lane_fraction"], 4),
        "kv_bytes_read_per_step": int(kv_bytes
                                      // max(1, c["decode_steps"])),
        "tokens_per_gb_kv_read": round(toks / (kv_bytes / 1e9), 1),
    }


def _bench_prefix_prefill(backend, on_tpu, rng):
    """Shared-prefix admission: 8 requests extending one 64-token system
    prompt, the workload prefix caching + batched prefill target.  Three
    admission modes ablate the two mechanisms:

      * per-request — submit+admit one at a time: one prefill dispatch
        per request (the PR-4 engine's admission shape);
      * batched     — submit all, co-bucketed admission: ONE prefill
        dispatch for all 8 lanes, every prompt fully recomputed;
      * prefix      — batched + warm prefix cache: ONE dispatch that
        gathers the cached 64-token prefix and prefills only the
        8-token suffixes.

    Each mode runs the workload twice unmeasured (compile + cache warm)
    then once timed; rows report avg/p95 TTFT (submit -> first token,
    queue + prefill included) and prefill dispatch counts as deltas over
    the timed pass."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, new_tokens = 768, 16
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, new_tokens = 128, 4

    system = rng.randint(0, cfg.vocab_size, 64).tolist()
    prompts = [system + rng.randint(0, cfg.vocab_size, 8).tolist()
               for _ in range(8)]
    sp = SamplingParams(max_new_tokens=new_tokens)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()

    def drive(eng, per_request):
        t0 = time.time()
        reqs = [eng.submit(p, sp) for p in prompts]
        if per_request:
            # PR-4 admission shape: strict-FIFO solo prefills, one
            # compiled dispatch per request (engine internals on
            # purpose — the public path always co-buckets now)
            while eng.scheduler.queue_depth and eng.cache.free_slots:
                eng._prefill_batch(eng.scheduler.admissible(1))
        while eng.scheduler.has_work:
            eng.step()
        return time.time() - t0, reqs

    rows = []
    for mode in ("per-request", "batched", "prefix"):
        eng = Engine(model, EngineConfig(
            num_slots=8, max_seq_len=max_seq,
            prefix_block_size=16 if mode == "prefix" else 0),
            register_profiler=False)
        drive(eng, mode == "per-request")   # warm compiles (+ cache)
        drive(eng, mode == "per-request")   # warm the warm-path bucket
        before = eng.counters()
        dt, reqs = drive(eng, mode == "per-request")
        after = eng.counters()
        eng.close()
        ttfts = sorted(r.ttft for r in reqs)
        hit = (after["prefix_hit_tokens"] - before["prefix_hit_tokens"])
        tot = (after["prompt_tokens"] - before["prompt_tokens"])
        rows.append({
            "metric": f"prefill TTFT shared-prefix 64tok x 8 reqs "
                      f"[{mode}] (+{new_tokens} new, {backend})",
            "value": round(sum(ttfts) / len(ttfts) * 1e3, 3),
            "unit": "ms avg TTFT",
            "ttft_p95_ms": round(ttfts[-1] * 1e3, 3),
            "prefill_dispatches": (after["prefill_calls"]
                                   - before["prefill_calls"]),
            "prefill_requests": (after["prefill_requests"]
                                 - before["prefill_requests"]),
            "prefix_hit_ratio": round(hit / tot, 3) if tot else 0.0,
            "wall_s": round(dt, 4),
        })
    return rows


def _bench_chunked_prefill(backend, on_tpu, rng):
    """Long-prompt arrival during an active b8 decode batch: the
    head-of-line-blocking workload chunked prefill targets.  Eight
    short-prompt requests stream greedily; once each has a few tokens
    out, one long prompt arrives.  Two admission modes:

      * whole   — prefill_chunk_tokens=0: the long prompt prefills in
        ONE dispatch at its full pow2 bucket, stalling every decode
        stream for that dispatch's duration;
      * chunked — the prompt prefills chunk-by-chunk, one chunk per
        decode boundary, so no single stall exceeds one chunk.

    Per decode stream we stamp token arrivals (max_horizon=1, so every
    token is individually stamped) and take inter-token gaps after the
    long submit; the p99 gap IS the interference number (with 8
    streams the stall lands in every stream's tail).  Rows report
    p99/max stall, the median gap as the unstalled TPOT floor, and the
    long request's TTFT (chunking trades TTFT for tail latency — the
    row pair quantifies both sides).

    Self-gated: token streams must be BITWISE identical across modes
    (chunking is a schedule change, not a numerics change), the
    chunked TTFT may not exceed 4x whole, and no chunked-mode prefill
    dispatch may exceed the chunk bucket while whole mode's long
    prompt lands in its full pow2 bucket — the deterministic form of
    "interference drops", since stall scales with the tokens a single
    dispatch prefills.  The measured p99-stall reduction is gated only
    where compute dominates (TPU): on CPU at bench scale a dispatch is
    fixed-overhead-bound, so a 64-token chunk costs the wall clock the
    same as a 256-token whole prefill and the wall ratio is noise.
    Prompts are fresh random tokens per trial (same shapes, so
    compiles stay warm) so the radix store never converts the measured
    prefill into a prefix hit.  Best-of-3 trials per mode."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, long_len, chunk, dec_len, dec_new = 1024, 768, 256, 32, 128
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=384)
        max_seq, long_len, chunk, dec_len, dec_new = 384, 256, 64, 16, 48

    sp_dec = SamplingParams(max_new_tokens=dec_new)
    sp_long = SamplingParams(max_new_tokens=4)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()

    def engine(chunk_tokens):
        return Engine(model, EngineConfig(
            num_slots=9, max_seq_len=max_seq, max_horizon=1,
            prefill_chunk_tokens=chunk_tokens,
            kv_pool_blocks=128 if not on_tpu else 0),
            register_profiler=False)

    def prompts_for(trial):
        # fresh tokens each trial: same SHAPES (warm compiles) but no
        # radix reuse — a prefix hit would erase the very prefill work
        # whose interference this section measures
        return ([rng.randint(0, cfg.vocab_size, dec_len).tolist()
                 for _ in range(8)],
                rng.randint(0, cfg.vocab_size, long_len).tolist())

    def drive(eng, dec_prompts, long_prompt):
        decoders = [eng.submit(p, sp_dec) for p in dec_prompts]
        while any(len(r.output_ids) < 4 for r in decoders):
            eng.step()
        long_req = eng.submit(long_prompt, sp_long)
        prev = [len(r.output_ids) for r in decoders]
        stamps = [[] for _ in decoders]
        while eng.scheduler.has_work:
            eng.step()
            now = time.time()
            for i, r in enumerate(decoders):
                n = len(r.output_ids)
                stamps[i].extend([now] * (n - prev[i]))
                prev[i] = n
        gaps = sorted(b - a for s in stamps for a, b in zip(s, s[1:]))
        p99 = gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))]
        med = gaps[len(gaps) // 2]
        streams = [r.output_ids for r in decoders] + [long_req.output_ids]
        return p99, gaps[-1], med, long_req.ttft, streams

    trials = 3
    prompt_sets = [prompts_for(t) for t in range(trials)]
    measured = {}                     # mode -> (p99, max, med, ttft)
    stream_sets = {}                  # mode -> per-trial token streams
    engines = {}
    for mode, ct in (("whole", 0), ("chunked", chunk)):
        eng = engines[mode] = engine(ct)
        drive(eng, *prompts_for(99))  # compile + cache warm, unmeasured
        runs, outs = [], []
        for dec_prompts, long_prompt in prompt_sets:
            p99, mx, med, ttft, streams = drive(eng, dec_prompts,
                                                long_prompt)
            runs.append((p99, mx, med, ttft))
            outs.append(streams)
        measured[mode] = tuple(min(v[k] for v in runs) for k in range(4))
        stream_sets[mode] = outs
    if stream_sets["chunked"] != stream_sets["whole"]:
        raise RuntimeError(
            "chunked prefill diverged from whole-prompt token streams")
    w_p99, w_max, w_med, w_ttft = measured["whole"]
    c_p99, c_max, c_med, c_ttft = measured["chunked"]
    pstats = {m: engines[m].stats()["prefill"] for m in engines}
    # deterministic interference gate: every chunked dispatch fit the
    # chunk bucket; the whole run really did prefill the long prompt
    # in one full-bucket dispatch
    c_big = max(b for _, b in pstats["chunked"]["buckets"])
    w_big = max(b for _, b in pstats["whole"]["buckets"])
    if c_big > chunk or w_big < long_len:
        raise RuntimeError(
            f"dispatch buckets contradict the modes: chunked max "
            f"{c_big} (chunk {chunk}), whole max {w_big} "
            f"(long prompt {long_len})")
    if on_tpu and c_p99 >= w_p99:
        # only gate the measured stall where prefill compute dominates
        # the dispatch — see the docstring for why cpu can't
        raise RuntimeError(
            f"chunked prefill did not cut decode-stall p99: "
            f"{c_p99 * 1e3:.2f} ms vs whole {w_p99 * 1e3:.2f} ms")
    ttft_gate = 4.0
    if c_ttft > ttft_gate * w_ttft:
        raise RuntimeError(
            f"chunked TTFT {c_ttft * 1e3:.1f} ms over the "
            f"{ttft_gate:.0f}x gate vs whole {w_ttft * 1e3:.1f} ms")
    stats = pstats["chunked"]
    counts = {m: engines[m].counters() for m in engines}
    for m in engines:
        engines[m].close()
    rows = []
    for mode, (p99, mx, med, ttft) in measured.items():
        row = {
            "metric": f"decode TPOT p99 stall, {long_len}-tok arrival "
                      f"mid-b8-decode [{mode}] ({backend})",
            "value": round(p99 * 1e3, 3),
            "unit": "ms p99 inter-token gap",
            "max_stall_ms": round(mx * 1e3, 3),
            "decode_floor_ms": round(med * 1e3, 3),
            "long_ttft_ms": round(ttft * 1e3, 3),
            "prefill_dispatches": counts[mode]["prefill_calls"],
            "max_dispatch_bucket": max(
                b for _, b in pstats[mode]["buckets"]),
        }
        if mode == "chunked":
            row["chunk_tokens"] = stats["chunk_tokens"]
            row["chunk_dispatches"] = counts[mode][
                "prefill_chunk_dispatches"]
            row["interference_seconds"] = round(
                stats["interference_seconds"], 4)
            row["stall_cut_pct"] = round(100.0 * (1 - p99 / w_p99), 1)
            row["ttft_ratio_vs_whole"] = round(ttft / w_ttft, 2)
        rows.append(row)
    return rows


def _bench_paged_ablation(backend, on_tpu, rng):
    """Ragged paged attention vs full-width table reads — the ablation
    behind the b8 fused-scan regression (scan128 b8: 2662.5 tok/s /
    3.005 ms/step vs 3156.1 / 2.535 per-step, 25.5% vs ~30% of the
    weight roofline).

    DIAGNOSIS of that regression: at b1 the scan wins 1.6x because it
    removes per-step dispatch (~1 ms host gap).  At b8 the step is
    device-bound (the async per-step driver already hides dispatch), so
    the scan gains nothing — and loses 0.47 ms/step because the slotted
    cache makes KV traffic scale with CAPACITY, not live tokens: every
    step masked-reads 8 full max_seq=768 rows (2*12L*768*1536*2B =
    56.6 MB/lane, 453 MB/step = 0.55 ms of bandwidth at 819 GB/s, vs
    0.07 MB of live-token writes), and inside ``lax.scan`` the
    dynamic-update-slice cache write forces the loop to materialize the
    full carried buffers again instead of updating in place.  The paged
    pool attacks exactly that scaling: decode writes touch one BLOCK
    per lane and ragged attention reads only table-mapped blocks, so
    per-step KV bytes track live length.

    Rows: ragged (table width bucketed to the deepest live row) vs full
    (``ragged_attention=False`` — width pinned to max_blocks_per_slot,
    the slotted-bandwidth shape) at a short and a long prompt.  Ragged
    should show (a) fewer KV bytes/step at short lengths — per-step
    cost DROPPING with shorter sequences — and (b) more decode tokens
    per GB of KV read; full-width reads the same bytes regardless.  On
    CPU the bytes accounting is exact but timings mostly measure
    dispatch overhead, so tokens_per_gb_kv_read is the load-bearing
    column there."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, new_tokens, dtype = 768, 64, jnp.bfloat16
        prompt_lens = (32, 512)
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, new_tokens, dtype = 64, 16, jnp.float32
        prompt_lens = (8, 40)

    itemsize = jnp.dtype(dtype).itemsize
    dim, ffn, vocab = (cfg.hidden_size, cfg.intermediate_size,
                       cfg.vocab_size)
    layer_w = (4 * dim * dim + 3 * dim * ffn) * cfg.num_hidden_layers
    weight_bytes = (layer_w + dim * vocab) * itemsize
    bw_gbs = _backend_bandwidth_gbs(backend)
    roofline_ms = weight_bytes / (bw_gbs * 1e9) * 1e3

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    sp = SamplingParams(max_new_tokens=new_tokens)
    rows = []
    for ragged in (True, False):
        for plen in prompt_lens:
            prompt = rng.randint(0, cfg.vocab_size, plen).tolist()
            eng = Engine(model, EngineConfig(
                num_slots=1, max_seq_len=max_seq, max_horizon=8,
                cache_dtype=dtype, ragged_attention=ragged),
                register_profiler=False)
            eng.submit(prompt, sp)                # warm the compiles
            while eng.scheduler.has_work:
                eng.step(horizon=8)
            eng.submit(prompt, sp)
            eng.admit()
            kv0 = eng.counters()["kv_bytes_read"]
            t0 = time.time()
            while eng.scheduler.has_work:
                eng.step(horizon=8)
            dt = time.time() - t0
            c = eng.stats()
            kv_bytes = c["kv_pool"]["kv_bytes_read"] - kv0
            eng.close()
            per_step_ms = dt * 1000.0 / new_tokens
            mode = "ragged" if ragged else "full-width"
            row = {
                "metric": f"engine paged-decode [{mode}] b1 prefill "
                          f"{plen} + {new_tokens} new ({backend})",
                "value": round(new_tokens / dt, 1),
                "unit": "tokens/s",
                "per_step_ms": round(per_step_ms, 3),
                "table_width_buckets": sorted(
                    {bk[1] for bk in c["decode_buckets"]}),
                "kv_bytes_read_per_step": int(kv_bytes // new_tokens),
                "tokens_per_gb_kv_read": round(new_tokens
                                               / (kv_bytes / 1e9), 1),
                "roofline_bw_gbs": bw_gbs,
                "weight_roofline_ms": round(roofline_ms, 3),
                "roofline_pct": round(
                    100.0 * roofline_ms / per_step_ms, 1),
            }
            rows.append(row)
    return rows


def _greedy_stream(model, prompt, new_tokens, max_seq):
    """One plain greedy generation; returns prompt + output as a list.
    Greedy decode is deterministic, so the continuation of any PREFIX
    of this stream is the rest of the stream — the property the spec
    bench's self-calibration below leans on."""
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    eng = Engine(model, EngineConfig(num_slots=1, max_seq_len=max_seq,
                                     max_horizon=8),
                 register_profiler=False)
    req = eng.submit(list(prompt), SamplingParams(max_new_tokens=new_tokens))
    while eng.scheduler.has_work:
        eng.step(horizon=8)
    eng.close()
    return list(prompt) + req.output_ids


def _spec_calibrate_prompt(model, rng, vocab, max_seq, new_tokens):
    """Derive a prompt whose greedy continuation is self-repetitive.

    A randomly-initialized model doesn't continue OUR repeated pattern,
    so a hand-written repetitive prompt measures nothing: the drafter
    only wins when the model's own output repeats.  Greedy decode from
    a tiny model does fall into an attractor, though, so the bench
    calibrates against it in two pilot generations:

      1. generate from an arbitrary pattern prompt and read the short
         cycle the stream's tail settled into;
      2. generate from that cycle repeated — such streams empirically
         collapse into a long constant run — and cut the prompt a few
         tokens INTO the longest run.

    By greedy determinism the continuation of that prefix is the rest
    of the run: a stream the n-gram drafter predicts from the first
    window.  This is the honest analogue of real repetitive serving
    traffic (code, templated text) for a random-weight model."""
    pilot = (rng.randint(0, vocab, 4).tolist() * 4)[:16]
    s1 = _greedy_stream(model, pilot, 48, max_seq)
    tail = s1[-8:]
    period = 1
    for period in (1, 2, 3, 4):
        if all(tail[i] == tail[i - period] for i in range(period, 8)):
            break
    s2 = _greedy_stream(model, (tail[-period:] * 16)[:16], 48, max_seq)
    run_start, run_len, i = 0, 1, 0
    while i < len(s2):
        j = i
        while j < len(s2) and s2[j] == s2[i]:
            j += 1
        if j - i > run_len:
            run_start, run_len = i, j - i
        i = j
    return s2[:min(run_start + 4, max_seq - new_tokens)]


def _bench_spec_decode(backend, on_tpu, rng):
    """Speculative-decode ablation: b1 and b8 greedy tok/s at draft
    width K in {0, 2, 4, 8} on two continuation profiles —

      * repetitive — a pilot-calibrated prompt whose greedy
        continuation repeats itself (see _spec_calibrate_prompt), so
        the prompt-lookup drafter's proposals land: accept length > 1
        multiplies single-stream tokens/s, the thing batching cannot
        do for b1;
      * random — an unstructured prompt whose continuation the n-gram
        drafter cannot predict: the floor case, paying the verify
        window for ~zero accepted drafts (``spec_adaptive`` exists
        precisely to shrink this case back to K=0 — the ablation pins
        it OFF to measure the raw cost).

    K=0 routes through the identical engine/scan code, so the random
    K=0 b1 row should sit within noise of the plain horizon-8 b1 row
    above (same shapes, one more KV block of table width).  Every row
    reports the accept-length telemetry from Engine.stats()."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, new_tokens = 768, 128
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, new_tokens = 96, 32

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompts = {
        "repetitive": _spec_calibrate_prompt(model, rng, cfg.vocab_size,
                                             max_seq, new_tokens),
        "random": rng.randint(0, cfg.vocab_size, 16).tolist(),
    }
    sp = SamplingParams(max_new_tokens=new_tokens)    # greedy
    rows = []
    for workload, prompt in prompts.items():
        for k in (0, 2, 4, 8):
            for n_req in ((1, 8) if workload == "repetitive" else (1,)):
                eng = Engine(model, EngineConfig(
                    num_slots=max(1, n_req), max_seq_len=max_seq,
                    max_horizon=8, spec_k=k, spec_adaptive=False),
                    register_profiler=False)
                batch = [list(prompt) for _ in range(n_req)]
                # warm every compile this run will touch
                for p in batch:
                    eng.submit(p, sp)
                while eng.scheduler.has_work:
                    eng.step(horizon=8)
                for p in batch:
                    eng.submit(p, sp)
                eng.admit()                # prefill outside the window
                t0 = time.time()
                while eng.scheduler.has_work:
                    eng.step(horizon=8)
                dt = time.time() - t0
                c = eng.stats()
                spec = c["spec"]
                eng.close()
                toks = n_req * new_tokens
                rows.append({
                    "metric": f"engine spec-decode tokens/s b{n_req} "
                              f"K{k} [{workload}] (prefill {len(prompt)}"
                              f" + {new_tokens} new, {backend})",
                    "value": round(toks / dt, 1),
                    "unit": "tokens/s",
                    "per_token_ms": round(dt * 1000.0 / toks, 3),
                    "spec_k": k,
                    "accept_rate": round(spec["accept_rate"], 4),
                    "mean_accept_len": round(spec["mean_accept_len"], 3),
                    "accept_len_hist": spec["accept_len_hist"],
                    "decode_horizons": c["decode_horizons"],
                })
    return rows


def _structured_vocab(size, eos_id=95):
    """Printable single-char tokens (ids 0..94), ``<eos>`` at 95, JSON
    skeleton multi-char tokens, ``<unusedN>`` padding to the model's
    vocab size — the token-string table the grammar compiler
    crossproducts against."""
    vocab = [chr(32 + i) for i in range(95)]
    vocab.append("<eos>")
    vocab.extend(['{"', '":', '",', '"}', '": "', '", "', '},{"',
                  'true', 'false', 'null', '["', '"]', '":"'])
    while len(vocab) < size:
        vocab.append(f"<unused{len(vocab)}>")
    return vocab


def _bench_structured(backend, on_tpu, rng):
    """Structured-generation ablation and gate: greedy tok/s on a JSON
    workload (array-of-objects schema, unbounded length so lanes run to
    the token budget) vs the free-text baseline, K in {0, 4}, forced
    drafting on/off.

    The acceptance gate: **structured decode with forced drafting must
    not be slower than free-text decode at the same draft width** — the
    grammar mask adds one gather + one ``where`` per window, and the
    JSON skeleton's sole-legal-token states hand the drafter free
    accepts that more than pay it back (same-K comparison isolates the
    constraint cost; the K-vs-0 speculation tradeoff is the spec_decode
    section's gate, and on a compute-bound CPU proxy the K+1-wide
    verify forward legitimately loses to width-1 decode).  Constrained
    rows also report forced-token and accept-length telemetry from
    ``stats()``."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, new_tokens = 768, 128
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, new_tokens = 96, 32

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    vocab, eos = _structured_vocab(cfg.vocab_size), 95
    schema = {"type": "array",
              "items": {"type": "object",
                        "properties": {"a": {"enum": ["x", "y"]},
                                       "b": {"type": "boolean"}},
                        "required": ["a", "b"]}}
    prompt = rng.randint(0, cfg.vocab_size, 16).tolist()
    n_req = 8
    variants = (
        ("free-text", 0, None, True),
        ("free-text", 4, None, True),
        ("structured", 0, schema, True),
        ("structured", 4, schema, False),     # plain n-gram drafts
        ("structured", 4, schema, True),      # + forced-token drafts
    )
    rows, tps = [], {}
    for workload, k, grammar, fd in variants:
        eng = Engine(model, EngineConfig(
            num_slots=n_req, max_seq_len=max_seq, max_horizon=8,
            spec_k=k, spec_adaptive=False,
            grammar_max_states=256 if grammar else 0,
            grammar_vocab=vocab if grammar else None,
            grammar_forced_drafting=fd), register_profiler=False)
        sp = (SamplingParams(max_new_tokens=new_tokens,
                             eos_token_id=eos) if grammar
              else SamplingParams(max_new_tokens=new_tokens))
        best, toks = None, 0
        for it in range(4):                    # it 0 warms the compiles
            reqs = [eng.submit(list(prompt), sp, grammar=grammar)
                    for _ in range(n_req)]
            eng.admit()                        # prefill outside window
            t0 = time.time()
            while eng.scheduler.has_work:
                eng.step(horizon=8)
            dt = time.time() - t0
            if it and (best is None or dt < best):
                best, toks = dt, sum(len(r.output_ids) for r in reqs)
        s = eng.stats()
        eng.close()
        key = (workload, k, fd)
        tps[key] = toks / best
        row = {
            "metric": f"engine structured tokens/s b{n_req} K{k} "
                      f"[{workload}{'+forced' if grammar and k and fd else ''}"
                      f"] (prefill {len(prompt)} + <= {new_tokens} new, "
                      f"{backend})",
            "value": round(tps[key], 1),
            "unit": "tokens/s",
            "per_token_ms": round(best * 1000.0 / toks, 3),
            "spec_k": k,
        }
        if grammar:
            row["forced_tokens"] = s["structured"]["forced_tokens"]
        if k:
            row["mean_accept_len"] = round(s["spec"]["mean_accept_len"],
                                           3)
        rows.append(row)
    # the gate: at the same draft width, the grammar mask + forced
    # drafting must not lose to free-text decode
    gated, baseline = tps[("structured", 4, True)], tps[("free-text", 4,
                                                         True)]
    print(f"structured+forced K4 {gated:.1f} tok/s vs free-text K4 "
          f"{baseline:.1f} tok/s (gate: >=)")
    assert gated >= baseline, (
        f"structured decode with forced drafting ({gated:.1f} tok/s) "
        f"slower than free-text at the same K ({baseline:.1f} tok/s)")
    return rows


def _bench_quant_ablation(backend, on_tpu, rng):
    """Quantized-serving ablation (int8 weight-only decode + int8 paged
    KV) — the PR-8 levers on the decode roofline's two byte streams:

      * fp     — knobs off: the exact PR-7 engine (bitwise-identical
        programs, asserted by TestQuantServing);
      * w8     — ``weight_dtype="int8"``: per-output-channel absmax PTQ
        of every Linear weight; programs read int8 + one fp scale row
        and dequantize inline, so the per-step weight stream shrinks
        ~4x (f32) / ~2x (bf16) while matmul math stays fp;
      * w8kv8  — plus ``kv_cache_dtype="int8"``: the paged pool stores
        int8 blocks with per-token fp32 scales beside the block table;
        quantize at append/COW, dequantize after the ragged gather.

    Throughput rows report tok/s, measured KV bytes/step (from the same
    block-table telemetry as every other row — int8 blocks + scale
    reads, not a formula), decode tokens per GB of KV traffic, and the
    resident weight bytes the step streams.  On CPU the timings mostly
    measure dispatch, so the bytes columns are the load-bearing ones
    (kv_bytes/step for w8kv8 must land <= 0.55x the fp row).

    The capacity row holds the pool BYTE budget fixed (what an HBM
    reservation actually is), sizes each mode's pool as
    budget // bytes_per_block, and drives an oversubscribed workload
    counting the peak number of concurrently-running sequences: int8 KV
    fits ~2x (bf16) / ~4x (f32) the sequences of the fp pool."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, new_tokens, dtype = 768, 64, jnp.bfloat16
        prompt_len = 512
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, new_tokens, dtype = 64, 16, jnp.float32
        prompt_len = 40

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    sp = SamplingParams(max_new_tokens=new_tokens)
    prompt = rng.randint(0, cfg.vocab_size, prompt_len).tolist()
    modes = (("fp", None, None),
             ("w8", "int8", None),
             ("w8kv8", "int8", "int8"))
    rows, bpb = [], {}
    for mode, wq, kq in modes:
        eng = Engine(model, EngineConfig(
            num_slots=1, max_seq_len=max_seq, max_horizon=8,
            cache_dtype=dtype, weight_dtype=wq, kv_cache_dtype=kq),
            register_profiler=False)
        bpb[mode] = eng.pool.bytes_per_block
        eng.submit(prompt, sp)                # warm the compiles
        while eng.scheduler.has_work:
            eng.step(horizon=8)
        eng.submit(prompt, sp)
        eng.admit()                           # prefill outside the window
        kv0 = eng.counters()["kv_bytes_read"]
        t0 = time.time()
        while eng.scheduler.has_work:
            eng.step(horizon=8)
        dt = time.time() - t0
        c = eng.stats()
        kv_bytes = c["kv_pool"]["kv_bytes_read"] - kv0
        eng.close()
        rows.append({
            "metric": f"engine quant-decode [{mode}] b1 prefill "
                      f"{prompt_len} + {new_tokens} new ({backend})",
            "value": round(new_tokens / dt, 1),
            "unit": "tokens/s",
            "per_step_ms": round(dt * 1000.0 / new_tokens, 3),
            "weight_dtype": wq or "fp",
            "kv_cache_dtype": kq or str(jnp.dtype(dtype)),
            "kv_store_dtype": c["kv_pool"]["dtype"],
            "kv_bytes_per_block": bpb[mode],
            "kv_bytes_read_per_step": int(kv_bytes // new_tokens),
            "tokens_per_gb_kv_read": round(new_tokens
                                           / (kv_bytes / 1e9), 1),
            "weight_bytes": c["quant"]["weight_bytes"],
        })

    # ---- capacity at a fixed pool byte budget: enough fp blocks for
    # ~4 sequences of this workload, then the same BYTES per mode
    seq_blocks = -(-(prompt_len + new_tokens) // 16)
    budget = (1 + 4 * seq_blocks) * bpb["fp"]
    n_req = 24

    def peak_running(kq, blocks):
        eng = Engine(model, EngineConfig(
            num_slots=n_req, max_seq_len=max_seq, max_horizon=4,
            cache_dtype=dtype, kv_cache_dtype=kq,
            kv_pool_blocks=blocks, prefix_block_size=0),
            register_profiler=False)
        for _ in range(n_req):
            eng.submit(prompt, sp)
        peak = 0
        while eng.scheduler.has_work:
            eng.step(horizon=4)
            peak = max(peak, len(eng.scheduler.running))
        pre = eng.counters().get("preemptions", 0)
        eng.close()
        return peak, pre

    cap = {}
    for mode, kq in (("fp", None), ("kv8", "int8")):
        blocks = max(2, budget // bpb["fp" if kq is None else "w8kv8"])
        cap[mode] = dict(zip(("peak", "preemptions"),
                             peak_running(kq, blocks)))
        cap[mode]["pool_blocks"] = blocks
    rows.append({
        "metric": f"engine quant kv-capacity fixed {budget} B pool, "
                  f"{n_req} reqs ({backend})",
        "value": round(cap["kv8"]["peak"] / max(1, cap["fp"]["peak"]),
                       2),
        "unit": "x peak concurrent seqs (int8 KV / fp)",
        "budget_bytes": budget,
        "bytes_per_block": {"fp": bpb["fp"], "int8": bpb["w8kv8"]},
        "fp": cap["fp"],
        "int8": cap["kv8"],
    })
    return rows


#: DECODE_BENCH.json row schema: 2 added per-row provenance
#: (schema_version, git_sha, run_id) so the bench trajectory is
#: reconstructable across PRs from the file's git history alone;
#: 3 adds roofline_bw_gbs — the per-backend bandwidth (datasheet or
#: memcpy-probed) every roofline column in the row was computed from
def _bench_sharded(backend, on_tpu, rng):
    """Tensor-parallel sharded serving: MeshEngine tp=2 vs the
    single-chip Engine on the same model, same workload, same knobs.

    HONESTY: on CPU the two tp 'devices' are VIRTUAL
    (--xla_force_host_platform_device_count) — both shards share one
    physical socket, so the tok/s ratio here measures the sharding
    machinery's overhead, NOT a speedup; treat the tp2 row as a
    correctness row.  What it pins: the streams are bitwise-equal to
    the single chip's, each shard's KV read share is
    ``kv_bytes_read / tp`` (the pool is head-sharded, every chip reads
    only its kv_heads/tp slice of every block), and the decode census
    matches the hand formula gated in MULTICHIP_BENCH.json.  On real
    multi-chip hardware the same rows become the speedup claim."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import (Engine, EngineConfig, MeshEngine,
                                    SamplingParams)

    if len(jax.devices()) < 2:
        print("[sharded] fewer than 2 devices visible — skipping "
              "(CPU runs need "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return []

    cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                    intermediate_size=512, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=128)
    max_seq, new_tokens, n_req, horizon = 96, 32, 4, 8
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompts = [rng.randint(0, cfg.vocab_size, 16).tolist()
               for _ in range(n_req)]
    sp = SamplingParams(max_new_tokens=new_tokens)
    ecfg = dict(num_slots=n_req, max_seq_len=max_seq,
                max_horizon=horizon)

    def measure(eng):
        # warm run compiles everything and yields the parity stream
        out = eng.generate([list(p) for p in prompts], sp)
        kv0 = eng.counters()["kv_bytes_read"]
        for p in prompts:
            eng.submit(list(p), sp)
        eng.admit()                     # prefill outside the window
        t0 = time.time()
        while eng.scheduler.has_work:
            eng.step(horizon=horizon)
        dt = time.time() - t0
        kv = eng.counters()["kv_bytes_read"] - kv0
        return out, dt, kv

    ref = Engine(model, EngineConfig(**ecfg), register_profiler=False)
    ref_out, ref_dt, ref_kv = measure(ref)
    ref.close()

    eng = MeshEngine(model, EngineConfig(**ecfg), tp=2,
                     register_profiler=False)
    out, dt, kv = measure(eng)
    bitwise = out == ref_out
    if not bitwise:                      # the row must not lie
        raise AssertionError("tp2 stream diverged from single chip")
    census = eng.decode_comms_report(horizon=horizon).counts()
    eng.close()

    toks = n_req * new_tokens
    tag = f"{backend}8"                  # 8 virtual devices
    return [
        {
            "metric": f"sharded decode tokens/s tp1 single-chip "
                      f"b{n_req} (prefill 16 + {new_tokens} new, {tag})",
            "value": round(toks / ref_dt, 1),
            "unit": "tokens/s",
            "per_token_ms": round(ref_dt * 1000.0 / toks, 3),
            "kv_bytes_read": ref_kv,
        },
        {
            "metric": f"sharded decode tokens/s tp2 mesh "
                      f"b{n_req} (prefill 16 + {new_tokens} new, {tag})",
            "value": round(toks / dt, 1),
            "unit": "tokens/s",
            "per_token_ms": round(dt * 1000.0 / toks, 3),
            "bitwise_equal_to_single_chip": bitwise,
            "virtual_devices": True,     # correctness row, no speedup claim
            "kv_bytes_read": kv,
            "kv_bytes_read_per_shard": kv // 2,
            "psum_calls_per_horizon": census[("psum", "tp")],
            "all_gather_calls_per_horizon": census[("all_gather", "tp")],
        },
    ]


def _bench_tracing_overhead(backend, on_tpu, rng):
    """Observability phase-2 overhead gate: the SAME b1 horizon-8
    decode stream as _bench_engine_horizons, run PAIRED in one process
    — once with request tracing + SLO tracking on (the serving
    default), once with ``request_tracing=False`` — so the overhead
    percentage compares two engines that differ ONLY in the flight
    record appends and SLO window observes on the hot path.  The traced
    row's tokens/s is the number the acceptance gate holds within 3 %
    of the horizon-8 engine baseline."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, prompt_len, new_tokens = 768, 512, 128
        dtype = jnp.bfloat16
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, prompt_len, new_tokens = 64, 16, 32
        dtype = jnp.float32

    horizon = 8
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = rng.randint(0, cfg.vocab_size, prompt_len).tolist()
    sp = SamplingParams(max_new_tokens=new_tokens)

    def run(traced):
        kw = dict(num_slots=1, max_seq_len=max_seq, max_horizon=16,
                  cache_dtype=dtype, request_tracing=traced)
        if traced:
            # generous thresholds: the gauge publishes fire per retire,
            # which is the cost being measured, not the breach math
            kw.update(slo_ttft_s=60.0, slo_tpot_s=10.0)
        eng = Engine(model, EngineConfig(**kw), register_profiler=False)
        # warm both compiles (prefill bucket + this horizon bucket)
        eng.submit(prompt, sp)
        while eng.scheduler.has_work:
            eng.step(horizon=horizon)
        best = None
        for _ in range(3):
            eng.submit(prompt, sp)
            eng.admit()               # prefill outside the decode timer
            t0 = time.time()
            while eng.scheduler.has_work:
                eng.step(horizon=horizon)
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        eng.close()
        return new_tokens / best

    off = run(False)
    on = run(True)
    return [{
        "metric": f"engine decode tokens/s b1 horizon{horizon} traced "
                  f"(prefill {prompt_len} + {new_tokens} new, "
                  f"{backend})",
        "value": round(on, 1),
        "unit": "tokens/s",
        "untraced_tokens_per_s": round(off, 1),
        "tracing_overhead_pct": round((off - on) / off * 100.0, 2),
    }]


def _bench_observatory_overhead(backend, on_tpu, rng):
    """Observability phase-3 overhead gate: the SAME paired-run shape
    as _bench_tracing_overhead, but both engines keep tracing + SLOs on
    (the PR 9 baseline) and differ ONLY in ``program_cards`` — the
    card probe at compile time plus the per-dispatch card lookup, cost
    share attribution, and roofline gauge on the hot path.  The carded
    row's tokens/s is the number the acceptance gate holds within 3 %
    of the cards-off baseline."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, prompt_len, new_tokens = 768, 512, 128
        dtype = jnp.bfloat16
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=128)
        max_seq, prompt_len, new_tokens = 64, 16, 32
        dtype = jnp.float32

    horizon = 8
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = rng.randint(0, cfg.vocab_size, prompt_len).tolist()
    sp = SamplingParams(max_new_tokens=new_tokens)

    def build(cards):
        eng = Engine(model, EngineConfig(
            num_slots=1, max_seq_len=max_seq, max_horizon=16,
            cache_dtype=dtype, request_tracing=True,
            slo_ttft_s=60.0, slo_tpot_s=10.0,
            program_cards=cards), register_profiler=False)
        # warm both compiles (prefill bucket + this horizon bucket)
        eng.submit(prompt, sp)
        while eng.scheduler.has_work:
            eng.step(horizon=horizon)
        return eng

    def timed(eng):
        eng.submit(prompt, sp)
        eng.admit()                   # prefill outside the decode timer
        t0 = time.time()
        while eng.scheduler.has_work:
            eng.step(horizon=horizon)
        return time.time() - t0

    # both engines warm, then ALTERNATE timed rounds: a sequential
    # A-then-B pairing is biased by process warm-up drift (the second
    # engine measures several percent faster on cpu regardless of
    # config), interleaving cancels it
    eng_off, eng_on = build(False), build(True)
    best_off = best_on = None
    for _ in range(4):
        dt = timed(eng_off)
        best_off = dt if best_off is None else min(best_off, dt)
        dt = timed(eng_on)
        best_on = dt if best_on is None else min(best_on, dt)
    eng_off.close()
    eng_on.close()
    off, on = new_tokens / best_off, new_tokens / best_on
    return [{
        "metric": f"engine decode tokens/s b1 horizon{horizon} carded "
                  f"(prefill {prompt_len} + {new_tokens} new, "
                  f"{backend})",
        "value": round(on, 1),
        "unit": "tokens/s",
        "uncarded_tokens_per_s": round(off, 1),
        "observatory_overhead_pct": round((off - on) / off * 100.0, 2),
    }]


def _bench_gateway(backend, on_tpu, rng):
    """Serving-gateway front-door overhead gate: TTFT for the SAME
    request measured twice — in-process (submit + step until the first
    token lands) and streamed over the gateway's HTTP/SSE path (POST
    /v1/completions with stream=true, timed to the first data frame).
    The engine is shared between the two phases (same weights, same
    warm compile caches; prefix cache off so neither phase warms the
    other), so the delta is exactly the front door: one localhost HTTP
    round-trip, the worker-thread submit hop, and the per-horizon SSE
    flush.  Gate: streamed TTFT within 15 % of in-process."""
    import http.client as _http

    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams
    from paddle_tpu.serving.gateway import Gateway, GatewayConfig

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        max_seq, prompt_len, new_tokens = 768, 512, 64
        dtype = jnp.bfloat16
    else:
        # bigger than the other cpu proxies on purpose: the gate is a
        # RATIO, and the front door's fixed cost (one localhost HTTP
        # round-trip + two thread handoffs, ~2 ms under the default
        # 5 ms GIL switch interval) needs a TTFT denominator that a
        # production request would actually have — against a 6 ms toy
        # prefill the percentage measures the GIL, not the gateway
        cfg = GPTConfig(vocab_size=4096, hidden_size=512,
                        intermediate_size=1024, num_hidden_layers=4,
                        num_attention_heads=8,
                        max_position_embeddings=256)
        max_seq, prompt_len, new_tokens = 160, 128, 16
        dtype = jnp.float32

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = rng.randint(0, cfg.vocab_size, prompt_len).tolist()

    def sp():
        return SamplingParams(max_new_tokens=new_tokens)

    eng = Engine(model, EngineConfig(
        num_slots=2, max_seq_len=max_seq, max_horizon=4,
        cache_dtype=dtype, prefix_cache_bytes=0),
        register_profiler=False)
    # warm the prefill bucket and the decode horizon compiles
    eng.submit(list(prompt), sp())
    while eng.scheduler.has_work:
        eng.step()

    # ---- in-process TTFT: submit is part of the serving path.
    # median, not min: TTFT is a handful of ms on cpu, and min-of-N
    # rewards whichever phase catches one lucky scheduler slice —
    # medians of both phases are stable run to run.
    trials = 7
    in_ts = []
    for _ in range(trials):
        t0 = time.time()
        req = eng.submit(list(prompt), sp())
        while req.n_generated < 1:
            eng.step()
        in_ts.append(time.time() - t0)
        while eng.scheduler.has_work:
            eng.step()
    med_in = sorted(in_ts)[trials // 2]

    # ---- the same engine behind the front door (it is idle now)
    gw = Gateway([eng], GatewayConfig()).start()
    body = json.dumps({"prompt": prompt, "max_tokens": new_tokens,
                       "stream": True})

    def streamed_ttft():
        conn = _http.HTTPConnection("127.0.0.1", gw.port, timeout=60)
        t0 = time.time()
        conn.request("POST", "/v1/completions", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        line = resp.fp.readline()            # first SSE data frame
        dt = time.time() - t0
        assert line.startswith(b"data: "), line
        resp.read()                          # drain to [DONE]
        conn.close()
        return dt

    streamed_ttft()                          # warm the HTTP path
    gw_ts = sorted(streamed_ttft() for _ in range(trials))
    med_gw = gw_ts[trials // 2]
    gw.shutdown()                            # drains + closes the engine

    overhead_pct = (med_gw - med_in) / med_in * 100.0
    if overhead_pct > 15.0:
        raise RuntimeError(
            f"gateway streamed TTFT {med_gw * 1e3:.2f} ms is "
            f"{overhead_pct:.1f}% over the in-process "
            f"{med_in * 1e3:.2f} ms (gate: 15%)")
    return [{
        "metric": f"gateway streamed TTFT ms b1 (prefill {prompt_len} "
                  f"+ {new_tokens} new, {backend})",
        "value": round(med_gw * 1e3, 3),
        "unit": "ms",
        "inprocess_ttft_ms": round(med_in * 1e3, 3),
        "gateway_overhead_pct": round(overhead_pct, 2),
        "gate_pct": 15.0,
    }]


def _bench_failover(backend, on_tpu, rng):
    """Mid-stream failover cost: one request is crashed out of its
    replica at a fixed dispatch ordinal and adopted by the survivor.
    Measures (a) recovery — wall time from the worker thread dying to
    the first post-failover token reaching the client — and (b) the
    whole-stream overhead against the same request run unbroken on the
    same warmed fleet.  The stream itself must come back bitwise equal
    to the unbroken run (that is the correctness gate; the timing gate
    is generous because recovery is dominated by the supervisor sweep
    interval and one re-prefill dispatch)."""
    import threading as _threading

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import (
        Engine, EngineConfig, FaultInjector, FaultPlan, FaultSpec,
        RetryPolicy, SamplingParams,
    )
    from paddle_tpu.serving.faults import SITE_WORKER_DISPATCH
    from paddle_tpu.serving.gateway import (
        EngineWorker, FleetSupervisor, PrefixAffinityRouter,
    )

    # the machinery under test is host-side (watchdog, adopt hop,
    # re-prefill admission), so the model is a small proxy on both
    # backends — recovery time is not a model-FLOPs measurement
    cfg = GPTConfig(vocab_size=128, hidden_size=64,
                    intermediate_size=128, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=64)
    prompt = rng.randint(1, cfg.vocab_size, 8).tolist()
    new_tokens = 24

    def sp():
        return SamplingParams(max_new_tokens=new_tokens)

    def drain(handle, stamps=None):
        got = []
        while True:
            kind, val = handle.events.get(timeout=120)
            if kind == "tokens":
                if stamps is not None:
                    stamps.extend([time.time()] * len(val))
                got.extend(val)
            else:
                return got, val

    paddle.seed(0)
    workers = []
    for i in range(2):
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        workers.append(EngineWorker(
            Engine(m, EngineConfig(num_slots=2, max_seq_len=64,
                                   max_horizon=4),
                   register_profiler=False), name=f"r{i}"))
    router = PrefixAffinityRouter(workers, retry=RetryPolicy())
    # warm every program the run needs: the base prefill bucket +
    # decode horizons, the bucket a resumed re-prefill lands in
    # (longer prompt), and the short-tail decode dispatches — resume
    # credits the already-streamed tokens, which shifts the stream's
    # horizon alignment onto (horizon, nb) buckets an unbroken run of
    # the same length never touches
    for w in workers:
        for p in (prompt, rng.randint(1, cfg.vocab_size, 12).tolist()):
            drain(w.submit(list(p), sampling=sp()))
        for n in (21, 22, 23):
            drain(w.submit(list(prompt),
                           sampling=SamplingParams(max_new_tokens=n)))

    # ---- unbroken reference on the warmed fleet (median of 5)
    trials = 5
    ref_tokens, unb = None, []
    for _ in range(trials):
        t0 = time.time()
        h, w, _ = router.submit(list(prompt), sampling=sp())
        got, fin = drain(h)
        unb.append(time.time() - t0)
        assert fin == "length" and len(got) == new_tokens
        ref_tokens = got
    med_unbroken = sorted(unb)[trials // 2]

    # ---- the crash run: one replica dies mid-stream, the survivor
    # adopts.  A 1 ms aliveness poll timestamps the death; the token
    # arrival stamps locate the first post-failover token.
    # a 5 ms sweep keeps dead-thread detection latency (uniform over
    # one interval) negligible next to the adopt + re-prefill work, so
    # the gated overhead ratio measures the machinery, not the cadence
    sup = FleetSupervisor(router, watchdog_timeout_s=None,
                          interval_s=0.005)
    target, _ = router.route(prompt)
    target.set_faults(FaultInjector(FaultPlan([
        FaultSpec(SITE_WORKER_DISPATCH, "crash", at=2)])))
    sup.start()
    crash_at = [None]

    def watch():
        while target._thread.is_alive():
            time.sleep(0.001)
        crash_at[0] = time.time()

    _threading.Thread(target=watch, daemon=True).start()
    stamps = []
    t0 = time.time()
    h, w, _ = router.submit(list(prompt), sampling=sp())
    got, fin = drain(h, stamps)
    total = time.time() - t0
    sup.stop()
    assert fin == "length"
    if got != ref_tokens:
        raise RuntimeError(
            "failed-over stream diverged from the unbroken run")
    if h.failovers != 1 or crash_at[0] is None:
        raise RuntimeError(
            f"expected exactly one failover (got {h.failovers})")
    # the first post-failover token is found by COUNT, not timestamp:
    # tokens flushed just before the crash can still be sitting in the
    # handle queue when the thread dies, so arrival stamps alone would
    # sometimes pick a pre-crash token and report a near-zero recovery
    resumed = int(h.request.trace.counts()["resumed_tokens"]
                  if h.request.trace else 0)
    if not 0 < resumed < len(stamps):
        raise RuntimeError(
            f"degenerate failover: {resumed} resumed tokens")
    recovery_ms = (stamps[resumed] - crash_at[0]) * 1e3
    overhead_pct = (total - med_unbroken) / med_unbroken * 100.0
    gate_ms = 5000.0
    if recovery_ms > gate_ms:
        raise RuntimeError(
            f"failover recovery {recovery_ms:.0f} ms over the "
            f"{gate_ms:.0f} ms gate")
    surviving = h.worker
    surviving.drain()
    assert surviving.engine.pool.blocks_in_use == 0
    for w in workers:
        if w.alive:
            w.stop()
    # the gated value is the overhead RATIO, not an absolute latency:
    # a ratio of two same-machine timings survives slow shared CI
    # runners where a 16 ms absolute recovery would flap; absolute
    # recovery still rides along (and self-gates above) for the reader
    return [{
        "metric": f"failover stream overhead pct (crash mid-stream, "
                  f"2 replicas, {backend})",
        "value": round(overhead_pct, 1),
        "unit": "% extra stream ms vs unbroken",
        "recovery_ms": round(recovery_ms, 2),
        "unbroken_stream_ms": round(med_unbroken * 1e3, 2),
        "failover_stream_ms": round(total * 1e3, 2),
        "resumed_tokens": resumed,
        "recovery_gate_ms": gate_ms,
    }]


def _bench_tiered_kv(backend, on_tpu, rng):
    """Tiered-KV crossover curve: resuming a preempted lane by host-
    arena swap-in (one batched host->device upload + graft, then a
    one-token suffix prefill) vs plain re-prefill of the whole context,
    swept over context length.  The per-ctx rows ARE the crossover
    curve — swap-in cost is ~O(context bytes / host link bandwidth)
    while re-prefill is O(context) model FLOPs, so the speedup column
    should cross 1.0 and grow with context.  ``modeled_upload_ms``
    normalizes the payload by the SAME ``host_device_bandwidth_gbs``
    figure the engine's auto policy divides by, so a reader can judge
    how far measured resume time sits above the pure-transfer floor.

    The storm row oversubscribes the pool (4 slots, ~2.5 lanes of
    blocks) so auto-preemption churns continuously, and compares total
    wall time policy "always" vs "never" — the aggregate win when
    every resume is a swap-in.

    Swap block/byte counts are pure functions of (context, block size,
    store dtype) and the deterministic schedule, so they gate exact
    through DETERMINISTIC_FIELDS; the timings carry the usual noise
    tolerance."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams
    from paddle_tpu.observability.memory import host_device_bandwidth_gbs

    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                        intermediate_size=4096, num_hidden_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024)
        ctx_lens, storm_ctx = (128, 256, 512), 256
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=256)
        ctx_lens, storm_ctx = (32, 64, 128), 128

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    sp = SamplingParams(max_new_tokens=8)
    bw = host_device_bandwidth_gbs(backend)
    reps = 3

    def resume_ms(policy, ctx):
        """Best-of-N admit() wall time for a just-preempted lane: the
        admission dispatch is where swap-in (or re-prefill) happens.
        The device radix is force-evicted after the preempt so the
        resume genuinely moves the WHOLE context — with the tier on
        the evictions demote and the swap-in re-uploads the chain,
        without it (kv_host_bytes=0, the recompute control) they drop
        and admission re-prefills every token.  Fresh prompts per rep
        so no rep inherits the previous one's radix; one full warm
        cycle first compiles the prefill buckets, decode, and the
        swap upload."""
        host_bytes = (64 << 20) if policy == "always" else 0
        eng = Engine(model, EngineConfig(
            num_slots=2, max_seq_len=ctx + 24, max_horizon=4,
            prefix_block_size=16, prefix_cache_bytes=4 << 20,
            kv_host_bytes=host_bytes, kv_swap_policy=policy),
            register_profiler=False)

        def cycle(timed):
            p = rng.randint(0, cfg.vocab_size, ctx).tolist()
            r = eng.submit(p, sp)
            eng.step(horizon=2)
            eng.preempt(r)
            eng.prefix.reclaim(10 ** 6)       # demote (or drop) it all
            t0 = time.time()
            eng.admit()
            dt = time.time() - t0
            eng.run()
            return dt if timed else None

        cycle(False)                          # warm the compiles
        best = min(cycle(True) for _ in range(reps))
        c = eng.counters()
        eng.close()
        return best * 1e3, c

    rows = []
    for ctx in ctx_lens:
        swap_ms, cs = resume_ms("always", ctx)
        reprefill_ms, _ = resume_ms("never", ctx)
        modeled_ms = cs["kv_swap_in_bytes"] / max(1, cs["kv_swap_ins"]) \
            / (bw * 1e9) * 1e3
        rows.append({
            "metric": f"engine tiered-kv resume ctx {ctx} swap-in vs "
                      f"re-prefill ({backend})",
            "value": round(reprefill_ms / max(swap_ms, 1e-9), 2),
            "unit": "x resume speedup (swap-in vs re-prefill)",
            "swap_resume_ms": round(swap_ms, 3),
            "reprefill_resume_ms": round(reprefill_ms, 3),
            "modeled_upload_ms": round(modeled_ms, 4),
            "host_bw_gbs": bw,
            "swap_ins": cs["kv_swap_ins"],
            "swap_outs": cs["kv_swap_outs"],
            "swap_in_bytes": cs["kv_swap_in_bytes"],
            "swap_out_bytes": cs["kv_swap_out_bytes"],
        })

    # ---- preemption storm: a priority burst preempts EVERY running
    # lane at the first boundary and force-reclaims the device radix
    # (the real-storm state: higher-priority arrivals take both the
    # slots and the blocks).  With the tier on the evictions demote
    # and each resume is a swap-in; the tier-free "never" control
    # drops everything and re-prefills whole contexts.  The wall-time
    # ratio charges the tier for ALL of its demotion device_gets, not
    # just the uploads it got to reuse.  Demotions are batched per
    # reclaim pass (PrefixCache.spill_batch: the force-reclaim below
    # pays one gather + device_get for every victim it evicts, not one
    # per block), so what this row now weighs is the residual aggregate
    # asymmetry: many small swap-in uploads against re-prefill
    # amortizing four lanes into one batched dispatch.
    n_req, bs = 8, 16
    prompt_blocks = -(-storm_ctx // bs)
    burst_rounds = 1

    def storm(policy):
        # the "never" control is a TIER-FREE engine: the recompute
        # alternative the crossover argues against is drop-and-
        # re-prefill, not pay-for-demotions-then-ignore-them
        host_bytes = (64 << 20) if policy == "always" else 0
        eng = Engine(model, EngineConfig(
            num_slots=4, max_seq_len=storm_ctx + 24, max_horizon=4,
            prefix_block_size=bs, prefix_cache_bytes=4 << 20,
            kv_pool_blocks=4 * (prompt_blocks + 1),
            kv_host_bytes=host_bytes, kv_swap_policy=policy),
            register_profiler=False)

        def pass_(timed):
            for _ in range(n_req):
                eng.submit(rng.randint(0, cfg.vocab_size,
                                       storm_ctx).tolist(), sp)
            t0 = time.time()
            boundary = 0
            while eng.scheduler.has_work:
                eng.step()
                boundary += 1
                if boundary <= burst_rounds:
                    for r in list(eng.scheduler.running.values()):
                        eng.preempt(r)
                    eng.prefix.reclaim(10 ** 6)
            return time.time() - t0 if timed else None

        pass_(False)                          # warm pass
        dt = pass_(True)
        c = eng.counters()
        eng.close()
        return dt, c

    swap_s, cs = storm("always")
    rec_s, cr = storm("never")
    rows.append({
        "metric": f"engine tiered-kv preemption-storm {n_req} reqs "
                  f"ctx {storm_ctx} ({backend})",
        "value": round(rec_s / max(swap_s, 1e-9), 2),
        "unit": "x storm wall speedup (swap-in vs re-prefill)",
        "swap_wall_s": round(swap_s, 4),
        "reprefill_wall_s": round(rec_s, 4),
        "preemptions": cs["preemptions"],
        "preemptions_reprefill": cr["preemptions"],
        "swap_ins": cs["kv_swap_ins"],
        "swap_outs": cs["kv_swap_outs"],
        "swap_in_bytes": cs["kv_swap_in_bytes"],
        "swap_out_bytes": cs["kv_swap_out_bytes"],
        "host_bw_gbs": bw,
    })
    return rows


SCHEMA_VERSION = 3


def _git_sha():
    """The repo HEAD this bench ran at (best-effort: 'unknown' outside
    a git checkout or without a git binary)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else "unknown"
    except (OSError, ValueError):
        return "unknown"


#: --only choices: "core" is the raw per-step/scan driver loop, the
#: rest map 1:1 onto the _bench_* section functions
SECTIONS = ("core", "engine_horizons", "engine", "paged_ablation",
            "prefix_prefill", "chunked_prefill", "spec_decode",
            "structured", "quant_ablation", "sharded",
            "tracing_overhead", "observatory_overhead", "gateway",
            "failover", "tiered_kv")


def main(argv=None):
    import argparse

    import jax
    import jax.numpy as jnp

    parser = argparse.ArgumentParser(
        description="decode-path benchmark suite")
    parser.add_argument(
        "--only", default=None,
        help="comma-separated section filter (choices: %s); a filtered "
             "run only replaces its OWN rows in DECODE_BENCH.json"
             % ",".join(SECTIONS))
    parser.add_argument(
        "--out", default=None,
        help="write this run's rows to FILE (fresh document, committed "
             "DECODE_BENCH.json untouched) — the input the check-bench "
             "regression gate compares against the committed baseline")
    args = parser.parse_args(argv)
    if args.only is None:
        only = set(SECTIONS)
    else:
        only = set(s.strip() for s in args.only.split(",") if s.strip())
        unknown = only - set(SECTIONS)
        if unknown:
            parser.error("unknown section(s) %s; choices: %s"
                         % (sorted(unknown), ",".join(SECTIONS)))

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    _numerics_gate(jnp.float32)

    if on_tpu:
        # GPT-438M proxy (bench.py's flagship config)
        L, dim, n_head, ffn, vocab = 12, 1536, 12, 4096, 32000
        prefill_len, n_steps, bsizes = 512, 128, (1, 8)
        dtype = jnp.bfloat16
    else:
        L, dim, n_head, ffn, vocab = 2, 256, 4, 512, 1024
        prefill_len, n_steps, bsizes = 32, 8, (1,)
        dtype = jnp.float32

    hd = dim // n_head
    max_seq = prefill_len + n_steps
    rng = np.random.RandomState(0)
    results = []

    # decode is weight-traffic-bound: every step reads all layer weights
    # + the LM head once from HBM (v5e ~819 GB/s, from the bandwidth
    # table; probed on CPU). KV-cache reads are tiny at this seq. This
    # roofline contextualizes per-step latency.
    itemsize = jnp.dtype(dtype).itemsize
    layer_w = (3 * dim * dim + dim * dim + 2 * dim * ffn) * L
    weight_bytes = (layer_w + dim * vocab) * itemsize
    bw_gbs = _backend_bandwidth_gbs(backend)
    roofline_ms = weight_bytes / (bw_gbs * 1e9) * 1e3

    for b in (bsizes if "core" in only else ()):
        P = {
            "layers": _build_params(rng, L, dim, n_head, ffn, dtype),
            "embed": jnp.asarray(
                (rng.randn(vocab, dim) * 0.02).astype(np.float32), dtype),
            "lm_head": jnp.asarray(
                (rng.randn(dim, vocab) * 0.02).astype(np.float32), dtype),
            "rotary": _rotary_tables(b, max_seq, hd, dtype),
        }
        jit_prefill, jit_step, jit_scan = _make_fns(
            L, dim, n_head, ffn, vocab, max_seq, dtype)
        ids = jnp.asarray(rng.randint(0, vocab, (b, prefill_len)),
                          jnp.int32)

        def fresh_caches():
            return [jnp.zeros((2, b, n_head, max_seq, hd), dtype)
                    for _ in range(L)]

        # ---- prefill (timed separately; also warms the compile)
        tok, caches = jit_prefill(P, ids, fresh_caches())
        tok.block_until_ready()
        t0 = time.time()
        tok, caches = jit_prefill(P, ids, fresh_caches())
        tok.block_until_ready()
        prefill_s = time.time() - t0

        # ---- per-step decode (donated caches), best-of-3 windows
        t = jnp.asarray(prefill_len, jnp.int32)
        jit_step(P, tok, t, caches)                   # compile
        best = None
        for _ in range(3):
            tok_w, caches_w = jit_prefill(P, ids, fresh_caches())
            tw0 = time.time()
            cur = tok_w
            for k in range(n_steps):
                cur, caches_w = jit_step(
                    P, cur, jnp.asarray(prefill_len + k, jnp.int32),
                    caches_w)
            cur.block_until_ready()
            dt = time.time() - tw0
            best = dt if best is None else min(best, dt)
        per_step_ms = best * 1000.0 / n_steps
        results.append({
            "metric": f"decode tokens/s/chip GPT-proxy {dtype.__name__} "
                      f"b{b} per-step (prefill {prefill_len} + "
                      f"{n_steps} steps, {backend})",
            "value": round(b * n_steps / best, 1),
            "unit": "tokens/s",
            "per_step_ms": round(per_step_ms, 3),
            "prefill_s": round(prefill_s, 4),
        })

        # ---- scan decode: 128 steps, ONE dispatch
        tok_w, caches_w = jit_prefill(P, ids, fresh_caches())
        toks, caches_s = jit_scan(P, tok_w, t, caches_w, n_steps)
        toks.block_until_ready()                      # compile
        best = None
        for _ in range(3):
            tok_w, caches_w = jit_prefill(P, ids, fresh_caches())
            tw0 = time.time()
            toks, _ = jit_scan(P, tok_w, t, caches_w, n_steps)
            toks.block_until_ready()
            dt = time.time() - tw0
            best = dt if best is None else min(best, dt)
        row = {
            "metric": f"decode tokens/s/chip GPT-proxy {dtype.__name__} "
                      f"b{b} scan{n_steps} ({backend})",
            "value": round(b * n_steps / best, 1),
            "unit": "tokens/s",
            "per_step_ms": round(best * 1000.0 / n_steps, 3),
            "weight_roofline_ms": round(roofline_ms, 3),
            "roofline_pct": round(
                100.0 * roofline_ms / (best * 1000.0 / n_steps), 1),
        }
        results.append(row)

    if "engine_horizons" in only:
        results.extend(_bench_engine_horizons(backend, on_tpu, rng))
    if "engine" in only:
        results.append(_bench_engine(backend, on_tpu, rng))
    if "paged_ablation" in only:
        results.extend(_bench_paged_ablation(backend, on_tpu, rng))
    if "prefix_prefill" in only:
        results.extend(_bench_prefix_prefill(backend, on_tpu, rng))
    if "chunked_prefill" in only:
        results.extend(_bench_chunked_prefill(backend, on_tpu, rng))
    if "spec_decode" in only:
        results.extend(_bench_spec_decode(backend, on_tpu, rng))
    if "structured" in only:
        results.extend(_bench_structured(backend, on_tpu, rng))
    if "quant_ablation" in only:
        results.extend(_bench_quant_ablation(backend, on_tpu, rng))
    if "sharded" in only:
        results.extend(_bench_sharded(backend, on_tpu, rng))
    if "tracing_overhead" in only:
        results.extend(_bench_tracing_overhead(backend, on_tpu, rng))
    if "observatory_overhead" in only:
        results.extend(_bench_observatory_overhead(backend, on_tpu, rng))
    if "gateway" in only:
        results.extend(_bench_gateway(backend, on_tpu, rng))
    if "failover" in only:
        results.extend(_bench_failover(backend, on_tpu, rng))
    if "tiered_kv" in only:
        results.extend(_bench_tiered_kv(backend, on_tpu, rng))

    # --out: a fresh standalone document for the check-bench gate —
    # provenance still stamped, committed DECODE_BENCH.json untouched
    if args.out is not None:
        sha = _git_sha()
        for r in results:
            r["schema_version"] = SCHEMA_VERSION
            r["git_sha"] = sha
            r["run_id"] = 0
            r.setdefault("roofline_bw_gbs", bw_gbs)
        for r in results:
            print(json.dumps(r))
        with open(args.out, "w") as f:
            json.dump({"backend": backend, "results": results},
                      f, indent=1)
        return

    # merge-preserving write: rows from OTHER backends (each metric
    # string ends with its backend tag, as "(cpu)" or "..., cpu)")
    # survive a re-run on this one; same-backend rows are replaced.
    # Every new row carries provenance — schema_version, the git SHA it
    # measured, and a run_id that increments monotonically over the
    # file's lifetime — so surviving old rows stay attributable.  Kept
    # rows are also deduped by metric (last write wins): an earlier
    # filter only matched the "(cpu)" spelling, so files written by it
    # can carry stale same-backend duplicates.
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "DECODE_BENCH.json")

    def _same_backend(metric):
        return metric.endswith((f"({backend})", f", {backend})"))

    # a full run replaces every same-backend row; a --only run replaces
    # just the metrics it re-measured, so the other sections' rows on
    # this backend survive
    new_metrics = {r["metric"] for r in results}

    def _keep(metric):
        if args.only is not None:
            return metric not in new_metrics
        return not _same_backend(metric)

    kept, run_id = [], 1
    if os.path.exists(out):
        try:
            with open(out) as f:
                prev = json.load(f)
            prev_rows = prev.get("results", [])
            latest = {}
            for r in prev_rows:
                if _keep(r.get("metric", "")):
                    latest[r.get("metric", "")] = r
            kept = list(latest.values())
            run_id = 1 + max((int(r.get("run_id", 0))
                              for r in prev_rows), default=0)
        except (ValueError, OSError):
            kept, run_id = [], 1
    sha = _git_sha()
    for r in results:
        r["schema_version"] = SCHEMA_VERSION
        r["git_sha"] = sha
        r["run_id"] = run_id
        # the bandwidth every roofline-bearing number in this run was
        # judged against (rows without roofline columns carry it too,
        # as run provenance)
        r.setdefault("roofline_bw_gbs", bw_gbs)
    for r in results:
        print(json.dumps(r))
    with open(out, "w") as f:
        json.dump({"backend": backend, "results": kept + results},
                  f, indent=1)


if __name__ == "__main__":
    sys.exit(main())
