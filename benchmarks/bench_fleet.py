#!/usr/bin/env python
"""Fleet-observatory benchmark (observability phase 5): the committed
FLEET_BENCH.json rows the ``check-bench`` regression gate enforces.

Two sections:

* ``sim_curve`` — SLO-attainment-vs-replica-count curves from the
  discrete-event capacity simulator (``fleetsim.simulate``) for the
  chat-heavy and mixed chat+batch workload shapes, under a PINNED
  reference service model (constants below, chosen near the live
  CPU-proxy calibration so the curves sit in the queueing-bound
  regime).  The simulator reads no clock and draws no randomness, so
  these rows are exact run-to-run — any drift is a real behavior
  change in the trace generator, the router/admission model, or the
  rollup math.
* ``calibration`` — the sim-vs-live loop: replay the no-abort
  ``calib`` workload probe over real HTTP/SSE against live 1- and
  2-replica CPU-proxy gateways (tiny identical-weight engines, warmed
  so jit compiles stay out of the measured run), calibrate a service
  model from the observed TTFT/TPOT, and gate the simulator's
  attainment predictions: replica-count ordering must be consistent
  (tie-aware — see ``fleetsim.calibration_report``) and worst
  attainment error within tolerance.  The calibration regime is
  deliberately UNCONTENDED: on a shared-core CI host, co-located
  replicas cannot beat one replica once host compute saturates, so
  the live side certifies the service-time model, while capacity
  scaling is the (deterministic) simulator's claim.

Gated ``value`` fields are all attainment-like fractions (higher is
better, robust at ~1.0) or the 0/1 ordering flag; noisy wall-clock
latencies ride along as ungated informational fields.

Prints one JSON line per metric; writes FLEET_BENCH.json at the repo
root when run there (merge-preserving, same provenance discipline as
bench_decode.py: schema_version, git sha, monotonic run_id).
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA_VERSION = 3

SECTIONS = ("sim_curve", "calibration")

#: pinned reference service model for the sim_curve rows — near the
#: live CPU-proxy calibration (prefill ~9 ms/token, decode ~7 ms/token
#: at max_horizon=1) so the curves are representative, but CONSTANT so
#: the rows never move unless the simulator/trace generator does
REF_MODEL = {"prefill_s_per_token": 9e-3,
             "decode_s_per_token": 7e-3,
             "overhead_s": 1e-3}

#: sim_curve knobs: heavy arrival rate + tight TTFT so the curve is
#: queueing-bound and strictly separates replica counts
SIM_RATE_RPS = 24.0
SIM_SPEED = 4.0
SIM_SLO = {"ttft_s": 0.35, "tpot_s": 0.25}
SIM_REPLICAS = (1, 2, 4)

#: calibration knobs: gentle load, generous SLO (the live gate must
#: not sit on a knife edge on a shared CI runner)
CAL_N_REQUESTS = 32
CAL_SPEED = 4.0
CAL_SLO = {"ttft_s": 2.0, "tpot_s": 0.5}
CAL_TOLERANCE = 0.25


def _git_sha():
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else "unknown"
    except Exception:
        return "unknown"


def _bench_sim_curve(backend):
    from paddle_tpu.observability import fleetsim, loadgen

    model = fleetsim.ServiceModel(**REF_MODEL)
    slo = loadgen.SLOSpec(**SIM_SLO)
    rows = []
    for shape in ("chat", "mixed"):
        trace = loadgen.generate(loadgen.SHAPES[shape](
            seed=0, n_requests=48, rate_rps=SIM_RATE_RPS))
        curve = fleetsim.attainment_curve(
            trace, SIM_REPLICAS, model, speed=SIM_SPEED, slo=slo)
        for c in curve:
            p95 = c["p95_ttft_s"]
            rows.append({
                "metric": (f"fleet sim attainment {shape} "
                           f"r{c['replicas']} seed0 ({backend})"),
                "value": c["attainment"],
                "unit": "attained fraction",
                # deterministic companions (informational; the sim is
                # exact, so value itself already gates at tolerance)
                "completed": c["completed"],
                "shed": c["shed"],
                "tokens_total": c["tokens_total"],
                "p95_ttft_ms": (round(p95 * 1e3, 2)
                                if p95 is not None else None),
                "trace_digest": trace.digest()[:12],
                "slo_ttft_s": SIM_SLO["ttft_s"],
                "rate_rps": SIM_RATE_RPS,
                "sim_speed": SIM_SPEED,
            })
    return rows


def _bench_calibration(backend):
    from paddle_tpu.observability import fleetsim, loadgen

    report = fleetsim.fleet_report(
        shapes=("calib",), replica_counts=(1, 2),
        n_requests=CAL_N_REQUESTS, seed=0, live=True, speed=CAL_SPEED,
        slo=loadgen.SLOSpec(**CAL_SLO), tolerance=CAL_TOLERANCE)
    cal = report["calibration"]
    live2 = report["live"]["reports"]["2"]
    ttft = live2["phase_latency"]["ttft_s"]
    tpot = live2["phase_latency"]["tpot_s"]
    rows = [
        {
            "metric": (f"fleet sim-vs-live attainment agreement "
                       f"calib ({backend})"),
            "value": round(1.0 - cal["max_abs_err"], 6),
            "unit": "agreement fraction",
            "max_abs_err": cal["max_abs_err"],
            "tolerance": cal["tolerance"],
            "calibration_rows": cal["rows"],
            "service_model": report["service_model"],
            "trace_digest": cal["trace_digest"][:12],
        },
        {
            "metric": (f"fleet sim-vs-live replica ordering "
                       f"consistent calib ({backend})"),
            "value": 1.0 if cal["ordering_consistent"] else 0.0,
            "unit": "bool",
            "ordering_exact": cal["ordering_exact"],
            "tie_eps": cal["tie_eps"],
        },
        {
            "metric": f"fleet live attainment calib r2 ({backend})",
            "value": live2["attainment"],
            "unit": "attained fraction",
            # wall-clock latencies are runner noise — informational
            "ttft_p50_ms": round(ttft["p50"] * 1e3, 2),
            "ttft_p95_ms": round(ttft["p95"] * 1e3, 2),
            "tpot_p50_ms": round(tpot["p50"] * 1e3, 2),
            "completed": live2["completed"],
            "tokens_total": live2["tokens_total"],
            "prefix_hit_ratio": live2["prefix_hit_ratio"],
        },
    ]
    return rows


def main(argv=None):
    import argparse

    import jax

    parser = argparse.ArgumentParser(
        description="fleet-observatory benchmark suite")
    parser.add_argument(
        "--only", default=None,
        help="comma-separated section filter (choices: %s); a filtered "
             "run only replaces its OWN rows in FLEET_BENCH.json"
             % ",".join(SECTIONS))
    parser.add_argument(
        "--out", default=None,
        help="write this run's rows to FILE (fresh document, committed "
             "FLEET_BENCH.json untouched) — the input the check-bench "
             "regression gate compares against the committed baseline")
    args = parser.parse_args(argv)
    if args.only is None:
        only = set(SECTIONS)
    else:
        only = set(s.strip() for s in args.only.split(",") if s.strip())
        unknown = only - set(SECTIONS)
        if unknown:
            parser.error("unknown section(s) %s; choices: %s"
                         % (sorted(unknown), ",".join(SECTIONS)))

    from paddle_tpu.observability.memory import backend_bandwidth_gbs

    backend = jax.default_backend()
    bw_gbs = backend_bandwidth_gbs(backend)
    results = []
    if "sim_curve" in only:
        results.extend(_bench_sim_curve(backend))
    if "calibration" in only:
        results.extend(_bench_calibration(backend))

    # --out: a fresh standalone document for the check-bench gate —
    # provenance still stamped, committed FLEET_BENCH.json untouched
    if args.out is not None:
        sha = _git_sha()
        for r in results:
            r["schema_version"] = SCHEMA_VERSION
            r["git_sha"] = sha
            r["run_id"] = 0
            r.setdefault("roofline_bw_gbs", bw_gbs)
        for r in results:
            print(json.dumps(r))
        with open(args.out, "w") as f:
            json.dump({"backend": backend, "results": results},
                      f, indent=1)
        return

    # merge-preserving write (bench_decode.py's discipline): rows from
    # OTHER backends survive, same-backend rows are replaced — all of
    # them on a full run, only the re-measured metrics on --only —
    # and every new row carries provenance with a monotonic run_id.
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "FLEET_BENCH.json")

    def _same_backend(metric):
        return metric.endswith((f"({backend})", f", {backend})"))

    new_metrics = {r["metric"] for r in results}

    def _keep(metric):
        if args.only is not None:
            return metric not in new_metrics
        return not _same_backend(metric)

    kept, run_id = [], 1
    if os.path.exists(out):
        try:
            with open(out) as f:
                prev = json.load(f)
            prev_rows = prev.get("results", [])
            latest = {}
            for r in prev_rows:
                if _keep(r.get("metric", "")):
                    latest[r.get("metric", "")] = r
            kept = list(latest.values())
            run_id = 1 + max((int(r.get("run_id", 0))
                              for r in prev_rows), default=0)
        except (ValueError, OSError):
            kept, run_id = [], 1
    sha = _git_sha()
    for r in results:
        r["schema_version"] = SCHEMA_VERSION
        r["git_sha"] = sha
        r["run_id"] = run_id
        r.setdefault("roofline_bw_gbs", bw_gbs)
    for r in results:
        print(json.dumps(r))
    with open(out, "w") as f:
        json.dump({"backend": backend, "results": kept + results},
                  f, indent=1)


if __name__ == "__main__":
    sys.exit(main())
