#!/usr/bin/env python
"""Benchmark the BASELINE.md model configs on one TPU chip.

Each benchmark compiles the full train step (fwd+bwd+optimizer) as one XLA
program via paddle.jit.TrainStep and reports best-of-3 windows (the shared
tunnel throttles ±15%; see BASELINE.md). The flagship GPT/LLaMA config is
benchmarked by the repo-root bench.py. Run:
python benchmarks/bench_models.py [resnet50|resnet50_f32|bert|unet|all]
("all" runs the bf16 resnet50 variant; resnet50_f32 reproduces the f32 row)
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _measure(step_fn, sync_out, units_per_step, steps=8, windows=3):
    step_fn()  # compile
    sync_out(step_fn())  # drain warmup before the first timed window
    best = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            out = step_fn()
        sync_out(out)
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return units_per_step * steps / best


def _measure_scan(step, batches, units_per_dispatch, scan_k):
    """Measure a K-steps-per-dispatch run (TrainStep.many): same per-step
    math as __call__, K× fewer host round-trips. Syncing on the summed
    loss vector drains the whole pack."""
    return _measure(lambda: step.many(batches),
                    lambda o: float(o.numpy().sum()), units_per_dispatch,
                    steps=max(2, 8 // scan_k))


_NOMINAL_PEAK_TF = 197.0  # v5e bf16 peak per chip


def _ceiling_tflops():
    """Measured practical matmul ceiling THROUGH THE TUNNEL, right now: a
    chain of 8192^3 bf16 matmuls in one program. The r1 measurement was
    ~92 TF (47% of nominal peak); measuring live keeps utilization
    numbers honest as tunnel conditions drift."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() not in ("tpu", "axon"):
        return None
    n, chain = 8192, 16

    @jax.jit
    def f(a, b):
        def body(c, _):
            return jnp.tanh(c @ b), ()
        out, _ = jax.lax.scan(body, a, None, length=chain)
        return out

    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)
    f(a, b).block_until_ready()
    best = None
    for _ in range(3):
        t0 = time.time()
        f(a, b).block_until_ready()
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return 2 * n ** 3 * chain / best / 1e12


def _flash_flops(b, heads, sq, skv, d, causal=False, remat=False):
    """Hand-counted FLOPs of one Pallas flash-attention call, fwd + bwd
    (VERDICT r3 weak #3: XLA's cost_analysis cannot see inside
    pallas_call, so flash-heavy models undercount utilization). fwd =
    QK^T + PV = 4·b·h·sq·skv·d (halved when causal block-skip applies);
    bwd ≈ 2.5× fwd (score recompute + dq + dk/dv kernels); remat runs
    the fwd once more inside the backward."""
    f = 4.0 * b * heads * sq * skv * d * (0.5 if causal else 1.0)
    return f * (3.5 + (1.0 if remat else 0.0))


def _utilization(result, step, batch, units_per_sec, units_per_step,
                 pallas_flops=0.0):
    """Attach the analytic utilization block: FLOPs/step from XLA's cost
    analysis of the exact compiled program PLUS the hand-counted Pallas
    kernel FLOPs (cost_analysis is blind inside pallas_call), achieved
    TFLOP/s, and % of both the nominal 197 TF peak and the live-measured
    tunnel ceiling (SURVEY §6: MFU is the north-star for every family)."""
    try:
        flops_xla = float(step.cost_analysis(*batch)["flops"])
    except Exception as e:  # cost analysis unsupported on this backend
        result["utilization_error"] = f"{type(e).__name__}: {e}"[:120]
        return result
    flops_per_step = flops_xla + pallas_flops
    tflops = units_per_sec / units_per_step * flops_per_step / 1e12
    result["flops_per_step"] = flops_per_step
    if pallas_flops:
        result["pallas_flops_per_step_est"] = round(pallas_flops)
    result["achieved_tflops"] = round(tflops, 1)
    result["pct_nominal_peak"] = round(100 * tflops / _NOMINAL_PEAK_TF, 1)
    ceiling = _ceiling_tflops()
    if ceiling:
        result["ceiling_tflops_now"] = round(ceiling, 1)
        result["pct_practical_ceiling"] = round(100 * tflops / ceiling, 1)
    return result


def bench_resnet50(dtype="bfloat16", B=64, scan_k=0):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    if dtype == "bfloat16":
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())

    def loss_fn(net, x, y):
        logits = net(x)
        if dtype == "bfloat16":
            logits = paddle.cast(logits, "float32")
        return nn.functional.cross_entropy(logits, y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(B, 3, 224, 224).astype(np.float32))
    if dtype == "bfloat16":
        x = paddle.cast(x, "bfloat16")
    y = paddle.to_tensor(rng.randint(0, 1000, (B,)).astype(np.int64))
    if scan_k:
        # isolates tunnel-dispatch latency from device throughput (r4
        # trace: device-side 2269 img/s at b64)
        ips = _measure_scan(step, [(x, y)] * scan_k, B * scan_k, scan_k)
    else:
        ips = _measure(lambda: step(x, y), lambda o: float(o), B)
    tag = "bf16" if dtype == "bfloat16" else "f32"
    scan_tag = f", scan{scan_k}" if scan_k else ""
    res = {"metric":
           f"images/sec ResNet-50 {tag} train (b{B}, 224px{scan_tag})",
           "value": round(ips, 1), "unit": "images/s"}
    return _utilization(res, step, (x, y), ips, B)


def bench_bert(B=32, scan_k=0, S=128):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models import BertConfig, BertForMaskedLM

    paddle.seed(0)
    cfg = BertConfig(vocab_size=30522, hidden_size=768,
                     num_hidden_layers=12, num_attention_heads=12,
                     intermediate_size=3072, max_position_embeddings=512)
    model = BertForMaskedLM(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    S = int(S)

    def loss_fn(net, ids, labels):
        out = net(ids, labels=labels)
        return out[0] if isinstance(out, tuple) else out

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 30522, (B, S)).astype(np.int32))
    if scan_k:
        sps = _measure_scan(step, [(ids, ids)] * scan_k, B * scan_k,
                            scan_k)
    else:
        sps = _measure(lambda: step(ids, ids), lambda o: float(o), B)
    scan_tag = f", scan{scan_k}" if scan_k else ""
    res = {"metric":
           f"sequences/sec BERT-base MLM bf16 train (b{B}xs{S}{scan_tag})",
           "value": round(sps, 1), "unit": "sequences/s"}
    pallas = 12 * _flash_flops(B, 12, S, S, 64)   # 12 bidirectional layers
    return _utilization(res, step, (ids, ids), sps, B, pallas_flops=pallas)


def bench_unet(B=4, scan_k=0):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models import UNetConfig, UNet2DConditionModel

    paddle.seed(0)
    cfg = UNetConfig()  # SD-style defaults from models/unet.py
    model = UNet2DConditionModel(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    def loss_fn(net, x, t, ctx, target):
        pred = net(x, t, ctx)
        return nn.functional.mse_loss(pred, target)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    lat = paddle.cast(paddle.to_tensor(
        rng.randn(B, cfg.in_channels, 32, 32).astype(np.float32)), "bfloat16")
    t = paddle.to_tensor(rng.randint(0, 1000, (B,)).astype(np.int32))
    ctx = paddle.cast(paddle.to_tensor(
        rng.randn(B, 77, cfg.cross_attention_dim).astype(np.float32)),
        "bfloat16")
    if scan_k:
        its = _measure_scan(step, [(lat, t, ctx, lat)] * scan_k, scan_k,
                            scan_k)
    else:
        its = _measure(lambda: step(lat, t, ctx, lat), lambda o: float(o), 1)
    scan_tag = f", scan{scan_k}" if scan_k else ""
    res = {"metric":
           f"iters/sec SD-UNet bf16 train (b{B}, 32x32 latents{scan_tag})",
           "value": round(its, 2), "unit": "iters/s"}
    return _utilization(res, step, (lat, t, ctx, lat), its, 1,
                        pallas_flops=_unet_attn_flops(cfg, B))


def _unet_attn_flops(cfg, B):
    """Per-step attention FLOPs of the SD-UNet's transformer blocks (self
    + cross per block), from the same topology the model builds: attn on
    down levels 0..n-2, the mid block, and up levels 1..n-1; spatial res
    halves after each non-final down level and doubles after each
    non-final up level (32x32 latents)."""
    heads = cfg.attention_head_dim
    chs = cfg.block_out_channels

    def pair(dim, res):
        s = res * res
        d = dim // heads
        if s < 128:
            # short rows take the XLA sdpa fallback (attention.py
            # _use_pallas: q seq >= 128) — cost_analysis already counts
            # those FLOPs; adding them here would double-count
            return 0.0
        return (_flash_flops(B, heads, s, s, d)          # self
                + _flash_flops(B, heads, s, 77, d))      # cross (ctx=77)

    total, res = 0.0, 32
    for i, c in enumerate(chs):
        if i < len(chs) - 1:
            total += cfg.layers_per_block * pair(c, res)
            res //= 2
    total += pair(chs[-1], res)                          # mid
    for i, c in enumerate(reversed(chs)):
        if i > 0:
            total += (cfg.layers_per_block + 1) * pair(c, res)
        if i < len(chs) - 1:
            res *= 2
    return total


def bench_llama():
    """LLaMA-family proxy for the BASELINE.json 13B stage-3+recompute config:
    the largest GQA preset that fits one 16 GB v5e chip (~0.9B params) with
    the exact feature set the 13B run would use — Pallas flash attention with
    native GQA, full-layer recompute (the single-chip analog of stage-3's
    free-the-activations strategy), fused chunked vocab CE, bf16 params with
    f32 optimizer moments."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=16,
                      num_attention_heads=16, num_key_value_heads=4,
                      max_position_embeddings=2048, use_recompute=True,
                      fused_lm_loss=True)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    n_params = sum(p.size for p in model.parameters())
    # no f32 master copy: moments are f32 already, and the proxy must leave
    # HBM room for activations (the 13B target offloads state instead)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    B, S = 8, 2048

    def loss_fn(net, ids, labels):
        loss, _ = net(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 32000, (B, S)).astype(np.int32))
    tps = _measure(lambda: step(ids, ids), lambda o: float(o), B * S)
    import jax

    peak = 197e12 if jax.default_backend() in ("tpu", "axon") else 1e12
    mfu = tps * 6 * n_params / peak
    res = {"metric": (f"tokens/sec/chip LLaMA-{n_params/1e6:.0f}M GQA "
                      f"bf16+recompute train (b{B}xs{S})"),
           "value": round(tps, 1), "unit": "tokens/s",
           "mfu_6N": round(mfu, 4)}
    pallas = 16 * _flash_flops(B, 16, S, S, 128, causal=True, remat=True)
    return _utilization(res, step, (ids, ids), tps, B * S,
                        pallas_flops=pallas)


def bench_gpt_longseq(seq=8192, batch=2):
    """Long-context single-chip row: the flagship GPT at s4096/s8192 with
    full recompute — Pallas flash keeps attention memory linear in seq
    (dense softmax OOMs at s4096); tok/s decline vs s1024 tracks
    attention's quadratic FLOPs share plus the remat re-forward."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=32000, hidden_size=1536,
                    intermediate_size=4096, num_hidden_layers=12,
                    num_attention_heads=12, max_position_embeddings=seq,
                    fused_lm_loss=True, use_recompute=True)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)

    def loss_fn(net, ids, labels):
        loss, _ = net(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, 32000, (batch, seq)).astype(np.int32))
    tps = _measure(lambda: step(ids, ids), lambda o: float(o), batch * seq,
                   steps=6)
    res = {"metric": (f"tokens/sec/chip GPT-438M bf16+recompute long-seq "
                      f"train (b{batch}xs{seq})"),
           "value": round(tps, 1), "unit": "tokens/s"}
    pallas = 12 * _flash_flops(batch, 12, seq, seq, 128, causal=True,
                               remat=True)
    return _utilization(res, step, (ids, ids), tps, batch * seq,
                        pallas_flops=pallas)


def bench_decode(B=8, L=16, dim=2048, n_head=16, prefill=512, steps=256,
                 max_seq=1024):
    """Generation throughput through the fused serving stack (ref: the
    fused_multi_transformer CUDA generation path): bf16 prefill writes
    the KV caches, then ONE compiled program scans `steps` single-token
    decodes (inline cache write + attend at the traced time_step).
    Decode is HBM-bound physics — every step re-reads all weights plus
    the live cache — so the report includes the analytic HBM roofline
    (v5e ~819 GB/s) and the fraction achieved."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.incubate.nn.functional as IF

    paddle.seed(0)
    rng = np.random.RandomState(0)
    hd = dim // n_head
    ffn = 4 * dim

    def mk(*sh):
        return paddle.cast(paddle.to_tensor(
            (rng.randn(*sh) * 0.02).astype(np.float32)), "bfloat16")

    P = dict(
        ln_scales=[mk(dim) + 1.0 for _ in range(L)],
        ln_biases=[mk(dim) for _ in range(L)],
        qkv_weights=[mk(3, n_head, hd, dim) for _ in range(L)],
        qkv_biases=[mk(3 * n_head * hd) for _ in range(L)],
        linear_weights=[mk(dim, dim) for _ in range(L)],
        linear_biases=[mk(dim) for _ in range(L)],
        ffn_ln_scales=[mk(dim) + 1.0 for _ in range(L)],
        ffn_ln_biases=[mk(dim) for _ in range(L)],
        ffn1_weights=[mk(dim, ffn) for _ in range(L)],
        ffn1_biases=[mk(ffn) for _ in range(L)],
        ffn2_weights=[mk(ffn, dim) for _ in range(L)],
        ffn2_biases=[mk(dim) for _ in range(L)],
    )
    x = paddle.cast(paddle.to_tensor(
        rng.randn(B, prefill, dim).astype(np.float32) * 0.3), "bfloat16")
    caches = [paddle.cast(paddle.to_tensor(
        np.zeros((2, B, n_head, max_seq, hd), np.float32)), "bfloat16")
        for _ in range(L)]

    # prefill as ONE compiled program (eager would pay a tunnel dispatch
    # per op — minutes of wall clock for zero information)
    def prefill_fn(x_arr, cache_arrs):
        with paddle.no_grad():
            o, nc = IF.fused_multi_transformer(
                paddle.Tensor(x_arr),
                cache_kvs=[paddle.Tensor(a) for a in cache_arrs], **P)
        return o._data, [c._data for c in nc]

    out_a, cache_arrays = jax.jit(prefill_fn, donate_argnums=(1,))(
        x._data, [c._data for c in caches])
    x0 = out_a[:, -1:, :]

    def decode_pack(cache_arrs, x_arr):
        def body(carry, i):
            arrs, xa = carry
            with paddle.no_grad():
                o, ncaches = IF.fused_multi_transformer(
                    paddle.Tensor(xa),
                    cache_kvs=[paddle.Tensor(a) for a in arrs],
                    time_step=paddle.Tensor(prefill + i), **P)
            return ([c._data for c in ncaches], o._data), ()

        (arrs, xa), _ = jax.lax.scan(
            body, (list(cache_arrs), x_arr),
            jnp.arange(steps, dtype=jnp.int32))
        return arrs, xa

    jitted = jax.jit(decode_pack, donate_argnums=(0,))
    arrs, xa = jitted(cache_arrays, x0)       # compile + warm
    jax.block_until_ready(xa)
    best = None
    for _ in range(3):
        t0 = time.time()
        arrs, xa = jitted(arrs, x0)
        jax.block_until_ready(xa)
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    toks = B * steps / best
    # analytic HBM roofline: per decode step, all weights stream once and
    # the valid cache prefix is read (k+v) once
    weight_bytes = sum(
        int(np.prod(t.shape)) * 2 for lst in P.values() for t in lst)
    avg_t = prefill + steps / 2
    cache_bytes = 2 * L * B * n_head * avg_t * hd * 2
    hbm_bw = 819e9                             # v5e nominal
    roof_step = (weight_bytes + cache_bytes) / hbm_bw
    roof_toks = B / roof_step
    return {"metric": (f"decode tokens/s fused_multi_transformer bf16 "
                       f"(L{L} dim{dim} b{B}, prefill{prefill}+"
                       f"{steps} steps)"),
            "value": round(toks, 1), "unit": "tokens/s",
            "ms_per_step": round(1e3 * best / steps, 3),
            "hbm_roofline_tokens_s": round(roof_toks, 1),
            "pct_hbm_roofline": round(100 * toks / roof_toks, 1),
            "weight_gb_per_step": round(weight_bytes / 1e9, 2),
            "cache_gb_per_step_avg": round(cache_bytes / 1e9, 2)}


def bench_ernie_hybrid():
    """ERNIE-style HybridParallel composition (BASELINE.json north-star
    family): tp2 x pp2 x dp2 on an 8-device mesh. On a single-chip box this
    runs on the virtual CPU mesh — correctness evidence (losses decrease
    under the full composition), perf N/A off-chip; on a real v5e/v5p pod
    slice the same code path gives the perf number."""
    import subprocess

    code = r"""
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import __graft_entry__ as g
g.dryrun_multichip(8)
print("HYBRID_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    t0 = time.time()
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ok = "HYBRID_OK" in r.stdout
    return {"metric": "ernie-hybrid tp*pp*dp composition (8-dev virtual mesh)",
            "value": 1 if ok else 0, "unit": "ok",
            "wall_s": round(time.time() - t0, 1),
            "detail": [l for l in r.stdout.splitlines() if "dryrun" in l][:6]
                      if ok else r.stderr[-300:]}


MULTICHIP_SCHEMA_VERSION = 1


def _git_sha():
    import subprocess

    try:
        r = subprocess.run(["git", "rev-parse", "--short=12", "HEAD"],
                           capture_output=True, text=True, timeout=10,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
        sha = r.stdout.strip()
        return sha if r.returncode == 0 and sha else "unknown"
    except (OSError, ValueError):
        return "unknown"


def bench_multichip_comms(out=None):
    """Collective-comms census + step timing of the explicit multichip
    configs (benchmarks/multichip_comms.py) on 8 virtual CPU devices.

    Rows carry the jaxpr walker's per-config collective counts by op
    (deterministic — gated EXACT by check-bench), the modeled ring
    wire bytes per step, and the comms-roofline share of the measured
    step.  Written with the DECODE_BENCH provenance discipline:
    ``out=None`` merge-writes the committed MULTICHIP_BENCH.json
    (run_id increments over the file's lifetime); ``out=FILE`` writes a
    fresh document with run_id 0 for ``check-bench --bench-file``."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = os.path.join(root, "benchmarks", "multichip_comms.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    t0 = time.time()
    r = subprocess.run([sys.executable, child], capture_output=True,
                       text=True, timeout=1800, env=env, cwd=root)
    rows, errors = [], []
    for line in r.stdout.splitlines():
        if not line.startswith("{"):
            continue
        row = json.loads(line)
        (errors if "error" in row else rows).append(row)
    ok = "MULTICHIP_COMMS_OK" in r.stdout and not errors
    sha = _git_sha()

    if out is not None:
        for row in rows:
            row["schema_version"] = MULTICHIP_SCHEMA_VERSION
            row["git_sha"] = sha
            row["run_id"] = 0
        with open(out, "w") as f:
            json.dump({"backend": "cpu8", "results": rows}, f, indent=1)
    elif rows:
        path = os.path.join(root, "MULTICHIP_BENCH.json")
        kept, run_id = [], 1
        if os.path.exists(path):
            try:
                with open(path) as f:
                    prev = json.load(f)
                prev_rows = prev.get("results", [])
                new_metrics = {row["metric"] for row in rows}
                latest = {}
                for row in prev_rows:
                    if row.get("metric", "") not in new_metrics:
                        latest[row.get("metric", "")] = row
                kept = list(latest.values())
                run_id = 1 + max((int(row.get("run_id", 0))
                                  for row in prev_rows), default=0)
            except (ValueError, OSError):
                kept, run_id = [], 1
        for row in rows:
            row["schema_version"] = MULTICHIP_SCHEMA_VERSION
            row["git_sha"] = sha
            row["run_id"] = run_id
        with open(path, "w") as f:
            json.dump({"backend": "cpu8", "results": kept + rows},
                      f, indent=1)
    for row in rows:
        print(json.dumps(row))
    return {"metric": "multichip comms suite (8-dev virtual mesh)",
            "value": len(rows), "unit": "configs",
            "ok": ok, "wall_s": round(time.time() - t0, 1),
            **({"errors": [e.get("error", "")[:120] for e in errors]}
               if errors else {})}


def main():
    argv = sys.argv[1:]
    out = None
    if "--out" in argv:
        i = argv.index("--out")
        out = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    which = argv[0] if argv else "all"
    benches = {"resnet50": bench_resnet50,
               "resnet50_f32": lambda: bench_resnet50(dtype="float32"),
               "bert": bench_bert,
               "unet": bench_unet,
               "unet_b16": lambda: bench_unet(B=16),
               "bert_b128": lambda: bench_bert(B=128),
               "resnet50_b256": lambda: bench_resnet50(B=256),
               "resnet50_scan8": lambda: bench_resnet50(scan_k=8),
               "bert_scan8": lambda: bench_bert(scan_k=8),
               "unet_scan8": lambda: bench_unet(scan_k=8),
               "decode": bench_decode,
               "gpt_s4096": lambda: bench_gpt_longseq(seq=4096, batch=4),
               "gpt_s8192": bench_gpt_longseq,
               "llama": bench_llama,
               "ernie_hybrid": bench_ernie_hybrid,
               "multichip_comms": lambda: bench_multichip_comms(out=out)}
    if which != "all" and which not in benches:
        print(f"unknown benchmark {which!r}; choose from "
              f"{sorted(benches)} or 'all'", file=sys.stderr)
        raise SystemExit(2)
    # "all" runs one variant per model family (bf16 resnet50); the f32
    # reproduction and throughput-optimal unet_b16 runs stay opt-in
    names = ([n for n in benches
              if n not in ("resnet50_f32", "unet_b16", "bert_b128",
                           "resnet50_b256", "resnet50_scan8", "bert_scan8",
                           "unet_scan8", "decode",
                           "gpt_s4096", "gpt_s8192", "multichip_comms")]
             if which == "all" else [which])
    if which == "all":
        # one fresh process per bench: HBM from a previous model (cached
        # executables, live donated buffers) must not shrink the next
        # model's budget — the llama proxy needs nearly the whole chip
        import subprocess

        me = os.path.abspath(__file__)
        for n in names:
            try:
                r = subprocess.run([sys.executable, me, n],
                                   capture_output=True, text=True,
                                   timeout=1800)
            except subprocess.TimeoutExpired:
                print(json.dumps({"metric": n, "error": "timeout after 1800s"}))
                continue
            out = [l for l in r.stdout.splitlines() if l.startswith("{")]
            print(out[-1] if out else json.dumps(
                {"metric": n, "error": r.stderr[-300:]}))
        return
    for n in names:
        try:
            print(json.dumps(benches[n]()))
        except Exception as e:  # report, keep going
            print(json.dumps({"metric": n, "error": f"{type(e).__name__}: {e}"[:300]}))


if __name__ == "__main__":
    main()
